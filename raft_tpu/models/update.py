"""Recurrent update blocks: motion encoders, ConvGRUs, flow/mask heads.

Parity targets: core/update.py:6-136.  NHWC; the SepConvGRU's 1x5/5x1
factorized convs are the large model's throughput trick and map well to the
MXU as two skinny matmuls.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from raft_tpu.models.layers import conv, kaiming_out


class ConvParams(nn.Module):
    """Parameter container structurally identical to an nn.Conv child.

    Lets the GRUs fuse sibling convolutions that share an input (z and r
    gates) into ONE conv at apply time — concatenating kernels along the
    output-channel axis is mathematically the same two convs, but fills
    the MXU with N=2*hidden instead of N=hidden — while the checkpoint
    tree keeps the reference's per-gate layout (convz1/kernel etc.), so
    .pth import and existing checkpoints are unaffected.
    """

    features: int
    kernel_size: Tuple[int, int]

    @nn.compact
    def __call__(self, in_features: int):
        w = self.param("kernel", kaiming_out,
                       self.kernel_size + (in_features, self.features))
        b = self.param("bias", nn.initializers.zeros_init(),
                       (self.features,))
        return w, b


def resolve_fused_update_block(cfg) -> bool:
    """RAFTConfig.fused_update_block tri-state -> the traced truth.

    ``None`` (auto) currently resolves OFF everywhere: the Pallas
    kernels (ops/gru_pallas.py) are parity- and gradient-proven in
    tier-1 but unmeasured on hardware, and — like DataConfig.device_aug
    — auto will stay off on CPU backends even after the chip A/B flips
    it on for TPU (interpret-mode kernels lose to XLA convs on CPU).
    ``True`` forces the fused path (tests and loss-parity gates do
    this; off-TPU it runs the kernels in interpret mode), ``False``
    forces the flax reference path.
    """
    if cfg.fused_update_block is not None:
        return bool(cfg.fused_update_block)
    return False


def _gru_params(hidden: int, cin: int, names_kernels, dtype):
    """ConvParams for a fused GRU in the checkpoint's exact tree layout
    (convz1/kernel etc.), cast to the compute dtype — the fused kernels
    consume raw weights, but .pth import and existing checkpoints see
    the same parameter names/shapes as the flax conv path.  Must be
    called from inside the owning module's compact scope."""
    out = {}
    for name, ks in names_kernels:
        w, b = ConvParams(hidden, ks, name=name)(cin)
        out[name] = (w.astype(dtype), b.astype(dtype))
    return out


def _fused_gate_conv(hx, z_name: str, r_name: str, hidden: int,
                     kernel: Tuple[int, int], dtype):
    """sigmoid(conv_z(hx)), sigmoid(conv_r(hx)) as one fused conv."""
    from jax.ad_checkpoint import checkpoint_name

    cin = hx.shape[-1]
    wz, bz = ConvParams(hidden, kernel, name=z_name)(cin)
    wr, br = ConvParams(hidden, kernel, name=r_name)(cin)
    w = jnp.concatenate([wz, wr], axis=-1).astype(dtype)
    b = jnp.concatenate([bz, br]).astype(dtype)
    pad = [(k // 2, k // 2) for k in kernel]
    out = jax.lax.conv_general_dilated(
        hx.astype(dtype), w, (1, 1), pad,
        dimension_numbers=("NHWC", "HWIO", "NHWC")) + b
    out = checkpoint_name(nn.sigmoid(out), "conv_out")
    return out[..., :hidden], out[..., hidden:]


class FlowHead(nn.Module):
    """conv3x3 -> relu -> conv3x3 to ``out_channels`` (update.py:6-14).

    ``out_channels`` defaults to the reference's 2 (dx, dy); the stereo
    workload instantiates the same head at 1 channel (disparity delta,
    workloads/stereo.py) — the parameter names are unchanged, so flow
    checkpoints are unaffected.
    """

    hidden_dim: int = 256
    dtype: Any = jnp.float32
    out_channels: int = 2

    @nn.compact
    def __call__(self, x):
        x = nn.relu(conv(self.hidden_dim, 3, dtype=self.dtype, name="conv1")(x))
        return conv(self.out_channels, 3, dtype=self.dtype, name="conv2")(x)


class ConvGRU(nn.Module):
    """3x3 convolutional GRU (update.py:16-31).

    ``fused=True`` routes through the halo-banded Pallas kernel
    (ops/gru_pallas.py conv_gru_pallas) — same math, same parameter
    tree, one launch per application instead of ~8 HLO ops."""

    hidden_dim: int = 128
    dtype: Any = jnp.float32
    fused: bool = False

    @nn.compact
    def __call__(self, h, x):
        if self.fused:
            from jax.ad_checkpoint import checkpoint_name

            from raft_tpu.ops.gru_pallas import conv_gru_pallas

            params = _gru_params(self.hidden_dim,
                                 h.shape[-1] + x.shape[-1],
                                 (("convz", (3, 3)), ("convr", (3, 3)),
                                  ("convq", (3, 3))), self.dtype)
            out = conv_gru_pallas(h.astype(self.dtype),
                                  x.astype(self.dtype), params)
            # not a dot: tag it saveable so dot-based remat policies
            # don't recompute the kernel in the backward scan
            # (resolve_remat_policy saves the name)
            return checkpoint_name(out, "fused_update")
        hx = jnp.concatenate([h, x], axis=-1)
        z, r = _fused_gate_conv(hx, "convz", "convr", self.hidden_dim,
                                (3, 3), self.dtype)
        q = nn.tanh(conv(self.hidden_dim, 3, dtype=self.dtype, name="convq")(
            jnp.concatenate([r * h, x], axis=-1)))
        return (1 - z) * h + z * q


class SepConvGRU(nn.Module):
    """Factorized 1x5 + 5x1 GRU (update.py:33-60).

    ``fused=True`` routes through the line-banded Pallas kernels
    (ops/gru_pallas.py sepconv_gru_pallas): each half — both gates,
    the q candidate and the convex update — is ONE launch with the
    sigmoid/tanh epilogues fused into the conv accumulation, plus one
    backward launch per half under AD.  Parameter tree unchanged."""

    hidden_dim: int = 128
    dtype: Any = jnp.float32
    fused: bool = False

    @nn.compact
    def __call__(self, h, x):
        if self.fused:
            from jax.ad_checkpoint import checkpoint_name

            from raft_tpu.ops.gru_pallas import sepconv_gru_pallas

            params = _gru_params(
                self.hidden_dim, h.shape[-1] + x.shape[-1],
                (("convz1", (1, 5)), ("convr1", (1, 5)),
                 ("convq1", (1, 5)), ("convz2", (5, 1)),
                 ("convr2", (5, 1)), ("convq2", (5, 1))), self.dtype)
            out = sepconv_gru_pallas(h.astype(self.dtype),
                                     x.astype(self.dtype), params)
            return checkpoint_name(out, "fused_update")
        # horizontal pass (1x5)
        hx = jnp.concatenate([h, x], axis=-1)
        z, r = _fused_gate_conv(hx, "convz1", "convr1", self.hidden_dim,
                                (1, 5), self.dtype)
        q = nn.tanh(conv(self.hidden_dim, (1, 5), dtype=self.dtype, name="convq1")(
            jnp.concatenate([r * h, x], axis=-1)))
        h = (1 - z) * h + z * q
        # vertical pass (5x1)
        hx = jnp.concatenate([h, x], axis=-1)
        z, r = _fused_gate_conv(hx, "convz2", "convr2", self.hidden_dim,
                                (5, 1), self.dtype)
        q = nn.tanh(conv(self.hidden_dim, (5, 1), dtype=self.dtype, name="convq2")(
            jnp.concatenate([r * h, x], axis=-1)))
        return (1 - z) * h + z * q


class SmallMotionEncoder(nn.Module):
    """Corr+flow feature mixer for the small model (update.py:62-77).

    ``fused=True``: the whole stack as one halo-banded Pallas launch
    (ops/gru_pallas.py small_motion_encoder_pallas); only the final
    ``concat([out, flow])`` stays in XLA so its gradient is automatic.
    """

    corr_channels: int  # corr_levels * (2r+1)^2
    dtype: Any = jnp.float32
    fused: bool = False

    @nn.compact
    def __call__(self, flow, corr):
        if self.fused:
            from jax.ad_checkpoint import checkpoint_name

            from raft_tpu.ops.gru_pallas import small_motion_encoder_pallas

            wts = []
            for name, co, k, ci in (("convc1", 96, 1, corr.shape[-1]),
                                    ("convf1", 64, 7, 2),
                                    ("convf2", 32, 3, 64),
                                    ("conv", 80, 3, 128)):
                w, b = ConvParams(co, (k, k), name=name)(ci)
                wts += [w.astype(self.dtype), b.astype(self.dtype)]
            flow = flow.astype(self.dtype)
            out = small_motion_encoder_pallas(
                flow, corr.astype(self.dtype), tuple(wts))
            out = checkpoint_name(out, "fused_update")
            return jnp.concatenate([out, flow], axis=-1)
        cor = nn.relu(conv(96, 1, dtype=self.dtype, name="convc1")(corr))
        flo = nn.relu(conv(64, 7, dtype=self.dtype, name="convf1")(flow))
        flo = nn.relu(conv(32, 3, dtype=self.dtype, name="convf2")(flo))
        out = nn.relu(conv(80, 3, dtype=self.dtype, name="conv")(
            jnp.concatenate([cor, flo], axis=-1)))
        return jnp.concatenate([out, flow], axis=-1)  # 80 + 2 = 82 channels


class BasicMotionEncoder(nn.Module):
    """Corr+flow feature mixer for the large model (update.py:79-97).

    ``fused=True``: the whole stack as one halo-banded Pallas launch
    (ops/gru_pallas.py basic_motion_encoder_pallas); only the final
    ``concat([out, flow])`` stays in XLA so its gradient is automatic.
    """

    corr_channels: int
    dtype: Any = jnp.float32
    fused: bool = False

    @nn.compact
    def __call__(self, flow, corr):
        if self.fused:
            from jax.ad_checkpoint import checkpoint_name

            from raft_tpu.ops.gru_pallas import basic_motion_encoder_pallas

            wts = []
            for name, co, k, ci in (("convc1", 256, 1, corr.shape[-1]),
                                    ("convc2", 192, 3, 256),
                                    ("convf1", 128, 7, 2),
                                    ("convf2", 64, 3, 128),
                                    ("conv", 126, 3, 256)):
                w, b = ConvParams(co, (k, k), name=name)(ci)
                wts += [w.astype(self.dtype), b.astype(self.dtype)]
            flow = flow.astype(self.dtype)
            out = basic_motion_encoder_pallas(
                flow, corr.astype(self.dtype), tuple(wts))
            out = checkpoint_name(out, "fused_update")
            return jnp.concatenate([out, flow], axis=-1)
        cor = nn.relu(conv(256, 1, dtype=self.dtype, name="convc1")(corr))
        cor = nn.relu(conv(192, 3, dtype=self.dtype, name="convc2")(cor))
        flo = nn.relu(conv(128, 7, dtype=self.dtype, name="convf1")(flow))
        flo = nn.relu(conv(64, 3, dtype=self.dtype, name="convf2")(flo))
        out = nn.relu(conv(126, 3, dtype=self.dtype, name="conv")(
            jnp.concatenate([cor, flo], axis=-1)))
        return jnp.concatenate([out, flow], axis=-1)  # 126 + 2 = 128 channels


class MaskHead(nn.Module):
    """Convex-upsample mask head (update.py:122-125; the 0.25 scale balances
    gradients, update.py:135).

    A sibling of the update block rather than a part of it: the mask only
    feeds the 8x upsampler, never the recurrence, so the model applies it
    OUTSIDE the refinement scan — batched over all iterates in train mode,
    final-iterate-only at inference (see models/raft.py).  Reference
    checkpoints' ``update_block.mask.*`` keys map here
    (utils/torch_import.py).
    """

    dtype: Any = jnp.float32
    # Optional override for mask_conv2's dtype (cfg.mask_conv2_f32);
    # None follows ``dtype``.  The f32 hypothesis (its output feeds the
    # f32 softmax anyway, and the bf16 backward fuses the bias-gradient
    # reduction into a 130 GB/s producer — 15.9 ms/step) LOST the A/B
    # by ~16 ms/step; measured record in docs/ARCHITECTURE.md.
    conv2_dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, net):
        c2 = self.conv2_dtype if self.conv2_dtype is not None else self.dtype
        mask = nn.relu(conv(256, 3, dtype=self.dtype, name="mask_conv1")(net))
        return 0.25 * conv(576, 1, dtype=c2,
                           name="mask_conv2")(mask.astype(c2))


class UncertaintyHead(nn.Module):
    """Per-pixel flow-confidence head off the context features.

    conv3x3 -> relu -> conv3x3 to ONE logit at 1/8 resolution; the
    model upsamples (bilinear — logits are smooth fields) to image
    resolution.  Trained against forward-backward-consistency occlusion
    masks (ops/consistency.py, workloads/uncertainty.py): a positive
    logit means "this flow vector has a visible correspondence and can
    be trusted".  Optional by construction — it hangs off
    ``RAFTConfig.uncertainty_head`` and flow-only checkpoints never see
    its parameters.
    """

    hidden_dim: int = 128
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, ctx):
        x = nn.relu(conv(self.hidden_dim, 3, dtype=self.dtype,
                         name="conf_conv1")(ctx))
        # f32 final conv: the logit feeds a sigmoid/BCE boundary
        return conv(1, 3, dtype=jnp.float32,
                    name="conf_conv2")(x.astype(jnp.float32))


class SmallUpdateBlock(nn.Module):
    """Motion encoder + ConvGRU + flow head; no upsample mask
    (update.py:99-112 — mask is None, so the model bilinearly upsamples)."""

    corr_channels: int
    hidden_dim: int = 96
    dtype: Any = jnp.float32
    # delta channels out of the head: 2 for flow (reference), 1 for the
    # stereo disparity workload (epipolar-constrained motion)
    head_channels: int = 2
    # route the motion encoder + GRU through the fused Pallas kernels
    # (RAFTConfig.fused_update_block via resolve_fused_update_block)
    fused: bool = False

    @nn.compact
    def __call__(self, net, inp, corr, flow):
        motion = SmallMotionEncoder(self.corr_channels, dtype=self.dtype,
                                    fused=self.fused,
                                    name="encoder")(flow, corr)
        x = jnp.concatenate([inp, motion], axis=-1)
        net = ConvGRU(self.hidden_dim, dtype=self.dtype,
                      fused=self.fused, name="gru")(net, x)
        delta = FlowHead(128, dtype=self.dtype,
                         out_channels=self.head_channels,
                         name="flow_head")(net)
        return net, delta


class BasicUpdateBlock(nn.Module):
    """Motion encoder + SepConvGRU + flow head (update.py:114-136).

    The reference computes the upsample mask here too; ours lives in
    :class:`MaskHead` so it can run outside the scan."""

    corr_channels: int
    hidden_dim: int = 128
    dtype: Any = jnp.float32
    # delta channels out of the head: 2 for flow (reference), 1 for the
    # stereo disparity workload (epipolar-constrained motion)
    head_channels: int = 2
    # route the motion encoder + GRU through the fused Pallas kernels
    # (RAFTConfig.fused_update_block via resolve_fused_update_block)
    fused: bool = False

    @nn.compact
    def __call__(self, net, inp, corr, flow):
        motion = BasicMotionEncoder(self.corr_channels, dtype=self.dtype,
                                    fused=self.fused,
                                    name="encoder")(flow, corr)
        x = jnp.concatenate([inp, motion], axis=-1)
        net = SepConvGRU(self.hidden_dim, dtype=self.dtype,
                         fused=self.fused, name="gru")(net, x)
        delta = FlowHead(256, dtype=self.dtype,
                         out_channels=self.head_channels,
                         name="flow_head")(net)
        return net, delta
