"""Shared NN building blocks: convs with Kaiming init and the four
normalization options of the reference encoders (extractor.py:16-38).

Parameters are always float32; ``dtype`` controls compute precision
(bf16 on TPU).  Norm statistics are computed in float32 by flax.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax.numpy as jnp

# torch nn.init.kaiming_normal_(mode='fan_out', nonlinearity='relu'):
# N(0, sqrt(2 / fan_out)) — extractor.py:150-157.
kaiming_out = nn.initializers.variance_scaling(2.0, "fan_out", "normal")


def conv(features: int, kernel: Union[int, Tuple[int, int]], stride: int = 1,
         *, dtype=jnp.float32, name: Optional[str] = None,
         padding: Optional[Sequence[Tuple[int, int]]] = None) -> Callable:
    """3x3/7x7/1x1 conv with torch-style symmetric padding (kernel//2).

    The output is tagged ``checkpoint_name(..., "conv_out")`` so the
    ``convs_and_dots_saveable`` remat policy (RAFTConfig.remat_policy) can
    keep conv outputs across the refinement scan's backward pass — XLA
    classifies convolutions as conv_general_dilated, which ``dots_saveable``
    alone would recompute.  The tag is inert under every other policy.
    """
    if isinstance(kernel, int):
        kernel = (kernel, kernel)
    if padding is None:
        padding = [(k // 2, k // 2) for k in kernel]

    def apply(x):
        from jax.ad_checkpoint import checkpoint_name
        y = nn.Conv(features, kernel, strides=(stride, stride),
                    padding=padding, kernel_init=kaiming_out, dtype=dtype,
                    name=name)(x)
        return checkpoint_name(y, "conv_out")

    return apply


class InstanceNorm(nn.Module):
    """Per-sample, per-channel spatial normalization.

    Matches torch nn.InstanceNorm2d defaults: affine=False,
    track_running_stats=False, eps=1e-5 (extractor.py:29-32 instantiates it
    with defaults, so there are no learnable parameters).
    """

    epsilon: float = 1e-5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        orig_dtype = x.dtype
        x32 = x.astype(jnp.float32)
        mean = x32.mean(axis=(1, 2), keepdims=True)
        var = x32.var(axis=(1, 2), keepdims=True)
        y = (x32 - mean) / jnp.sqrt(var + self.epsilon)
        return y.astype(orig_dtype)


def make_norm(norm_fn: str, channels: int, *, dtype=jnp.float32,
              train: bool = True, name: str = "norm") -> Callable:
    """Normalization factory for the encoder's norm_fn option
    (extractor.py:16-38): group | batch | instance | none.

    For 'batch', ``train=False`` means use running averages (the reference's
    freeze_bn eval()-mode BN, raft.py:58-61 / train.py:147-148).
    """
    if norm_fn == "group":
        return nn.GroupNorm(num_groups=max(channels // 8, 1), epsilon=1e-5,
                            dtype=dtype, name=name)
    if norm_fn == "batch":
        return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                            epsilon=1e-5, dtype=dtype, name=name)
    if norm_fn == "instance":
        return InstanceNorm(dtype=dtype, name=name)
    if norm_fn == "none":
        return lambda x: x
    raise ValueError(f"unknown norm_fn: {norm_fn}")
