"""Tiled high-resolution (4K) inference through the bucketed batcher.

A 2160x3840 frame does not fit any serving bucket family — and should
not: a single 4K executable would monopolize device memory for a shape
almost no request carries.  Instead, the frame is cut into overlapping
tiles of ONE static tile family, each tile rides the existing
queue -> batcher -> AOT executor path as an ordinary request (batched
with other tiles and with unrelated traffic of the same family), and
the per-tile flows are blended back with feathered seams:

- **Tiling**: a fixed grid with ``overlap`` pixels of shared context
  between neighbors; the last row/column is anchored to the frame edge
  so every pixel is covered by at least one tile and tiles never pad
  (:func:`plan_tiles`).  Optical flow is resolution-local, so a tile's
  flow needs no rescaling — only vectors that leave the tile lose
  their match, which is why the overlap must exceed the expected
  displacement magnitude and the blend discounts tile borders.
- **Blending**: per-tile weights ramp linearly from 0 at any edge that
  has a neighboring tile to 1 inside the core (:func:`tile_weights` —
  a separable feather), and the accumulated weight map normalizes the
  sum, so seams are C0-continuous and every pixel's weights sum to
  exactly 1 (:func:`blend_tiles` divides by the accumulated map).
  Frame edges keep full weight — there is no second opinion there.
- **Serving**: :func:`submit_tiled` fans the tiles into
  ``server.submit`` (one future per tile) and returns a combined
  future; the tiles are independent requests, so deadline sheds and
  poison isolation apply per tile and a typed per-tile rejection
  fails the whole frame typed (never a silently half-blended flow).

``abstract_tiled_forward`` is the registered lowerable entry point
(``tiled_serve_forward`` in ``raft_tpu/entrypoints.py``): the serving
forward at the TILE family's static shape, so the tile executable is
audited, budgeted and cache-warmed like every other graph.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

import numpy as np

# The default 4K tile family: /8-divisible, covers 2160x3840 in a 5x5
# grid at 64 px overlap.  Small enough that the executable's footprint
# stays in the same class as the video families, big enough that the
# 64-px feather is context, not the whole tile.
DEFAULT_TILE_HW = (544, 960)
DEFAULT_OVERLAP = 64
TILE_FAMILY = "tile4k"


def tiled_buckets(tile_hw: Tuple[int, int] = DEFAULT_TILE_HW,
                  base: Optional[Dict] = None) -> Dict[str,
                                                       Tuple[int, int]]:
    """The bucket table with the tile family added — what a
    tiled-serving FlowServer is constructed with."""
    from raft_tpu.serve.engine import default_buckets

    out = dict(base if base is not None else default_buckets())
    out[TILE_FAMILY] = tuple(tile_hw)
    return out


def plan_tiles(hw: Tuple[int, int], tile_hw: Tuple[int, int],
               overlap: int) -> List[Tuple[int, int]]:
    """Top-left (y, x) offsets of a covering tile grid.

    Stride is ``tile - overlap``; the final row/column snaps to the
    frame edge (so the last overlap may be larger, never smaller, and
    no tile hangs off the frame).  A frame no larger than one tile is
    a single tile at the origin."""
    H, W = hw
    th, tw = tile_hw
    if overlap < 0 or overlap >= min(th, tw):
        raise ValueError(f"overlap {overlap} must be in [0, "
                         f"min{tile_hw}) — a tile must advance")
    if th > H or tw > W:
        raise ValueError(f"tile {tile_hw} exceeds the frame {hw}; "
                         f"serve the frame as an ordinary request")

    def starts(total: int, tile: int) -> List[int]:
        if total <= tile:
            return [0]
        stride = tile - overlap
        out = list(range(0, total - tile, stride))
        out.append(total - tile)       # snap the last tile to the edge
        return out

    return [(y, x) for y in starts(H, th) for x in starts(W, tw)]


def tile_weights(hw: Tuple[int, int], tile_hw: Tuple[int, int],
                 origin: Tuple[int, int], overlap: int) -> np.ndarray:
    """(th, tw) feather weights for the tile at ``origin``: a linear
    ramp over the first/last ``overlap`` rows/cols on every side that
    has a neighboring tile, full weight elsewhere (frame edges)."""
    H, W = hw
    th, tw = tile_hw
    y, x = origin

    def axis(n: int, lo_ramp: bool, hi_ramp: bool) -> np.ndarray:
        # min-composed profiles, NOT in-place slice writes: when
        # overlap > n/2 the lo and hi ramps share indices, and a slice
        # write would let one overwrite the other mid-ramp — a weight
        # discontinuity at index n-overlap that breaks the C0 seam
        # contract.  min() of the two ramps is identical for
        # overlap <= n/2 and stays continuous for any overlap < n.
        w = np.ones(n, np.float32)
        if overlap > 0:
            idx = np.arange(n, dtype=np.float32)
            if lo_ramp:
                w = np.minimum(w, (idx + 1.0) / (overlap + 1))
            if hi_ramp:
                w = np.minimum(w, (n - idx) / (overlap + 1))
        return w

    wy = axis(th, lo_ramp=y > 0, hi_ramp=y + th < H)
    wx = axis(tw, lo_ramp=x > 0, hi_ramp=x + tw < W)
    return wy[:, None] * wx[None, :]


def blend_tiles(hw: Tuple[int, int], tile_hw: Tuple[int, int],
                plan: List[Tuple[int, int]], overlap: int,
                tile_flows: List[np.ndarray]) -> np.ndarray:
    """Feather-blend per-tile (th, tw, C) outputs into one (H, W, C)
    field.  Weights are normalized by the accumulated map, so they sum
    to exactly 1 everywhere regardless of how many tiles overlap."""
    H, W = hw
    th, tw = tile_hw
    C = tile_flows[0].shape[-1]
    acc = np.zeros((H, W, C), np.float32)
    wsum = np.zeros((H, W, 1), np.float32)
    for (y, x), flow in zip(plan, tile_flows):
        w = tile_weights(hw, tile_hw, (y, x), overlap)[..., None]
        acc[y:y + th, x:x + tw] += w * flow.astype(np.float32)
        wsum[y:y + th, x:x + tw] += w
    return acc / wsum


def submit_tiled(server, image1: np.ndarray, image2: np.ndarray,
                 tile_hw: Tuple[int, int] = DEFAULT_TILE_HW,
                 overlap: int = DEFAULT_OVERLAP,
                 deadline_ms: Optional[float] = None,
                 workload: str = "flow") -> Future:
    """Fan one high-res pair into tile requests and return a future
    for the blended full-res flow.

    Each tile is an ordinary admitted request (typed admission,
    deadline, poison isolation all apply per tile); any tile's typed
    rejection rejects the FRAME's future with that same error — a
    partially-served frame is never silently blended.  The result dict
    carries ``flow`` (H, W, 2 blended), ``tiles`` (the tile count) and
    ``iters`` (of the first tile — all tiles ride the same ladder)."""
    hw = image1.shape[:2]
    plan = plan_tiles(hw, tile_hw, overlap)
    th, tw = tile_hw
    futures = []
    out: Future = Future()
    # frame-level trace: the tile requests each carry their own trace
    # context under the SAME id (the fan-in join key), and the frame
    # context owns the phases no tile sees — fan-out, the wait for the
    # slowest tile, and the feather blend
    tracer = getattr(server, "tracer", None)
    ftr = (tracer.begin(rid="frame", workload=workload, family="tiled")
           if tracer is not None else None)
    for (y, x) in plan:
        t1 = np.ascontiguousarray(image1[y:y + th, x:x + tw])
        t2 = np.ascontiguousarray(image2[y:y + th, x:x + tw])
        try:
            futures.append(server.submit(
                t1, t2, deadline_ms=deadline_ms, workload=workload,
                **({"trace_id": ftr.tid} if ftr is not None else {})))
        except Exception as e:  # typed admission rejection of a tile
            # rejects the frame with the SAME typed error
            for f in futures:
                f.cancel()
            if ftr is not None:
                tracer.finish(
                    ftr, f"rejected:{getattr(e, 'kind', 'bad-request')}")
            out.set_exception(e)
            return out
    if ftr is not None:
        ftr.stamp("fan-out")
        ftr.event("tiles", n=len(plan))
    remaining = [len(futures)]
    lock = threading.Lock()
    results: List[Optional[Dict]] = [None] * len(futures)

    def blend_and_resolve() -> None:
        # claim the frame future exactly once: if the consumer already
        # cancelled it, drop the blend instead of racing set_result
        # into InvalidStateError on this thread
        if not out.set_running_or_notify_cancel():
            return
        if ftr is not None:
            # everything since fan-out was waiting on the slowest tile
            ftr.stamp("tile-wait")
        try:
            flows = [r["flow"] for r in results]
            blended = blend_tiles(hw, tile_hw, plan, overlap, flows)
            if ftr is not None:
                ftr.stamp("blend")
            out.set_result({"flow": blended, "tiles": len(plan),
                            "iters": results[0]["iters"]})
            if ftr is not None:
                tracer.finish(ftr, "served")
        except Exception as e:  # noqa: BLE001 — a blend failure
            # rejects the frame; it must never pass silently
            if ftr is not None:
                tracer.finish(ftr, "rejected:blend-failure")
            out.set_exception(e)

    def finish(i: int, f) -> None:
        exc = f.exception()
        with lock:
            if out.done():
                return
            if exc is not None:
                # the done() check above runs under OUR lock, not the
                # future's — a consumer cancel can still land between
                # it and the terminal, so claim before resolving
                if out.set_running_or_notify_cancel():
                    if ftr is not None:
                        tracer.finish(ftr, "rejected:" + getattr(
                            exc, "kind", "tile-failure"))
                    out.set_exception(exc)
                return
            results[i] = f.result()
            remaining[0] -= 1
            if remaining[0]:
                return
        # the last tile's done-callback runs ON the server's batcher
        # thread; a 4K feather blend there (tens of ms of numpy over
        # ~66 MB of accumulators) would stall every co-tenant batch,
        # inflating the exact p95 the SLO gate measures — hand it off
        threading.Thread(target=blend_and_resolve, daemon=True,
                         name="tiled-blend").start()

    for i, f in enumerate(futures):
        f.add_done_callback(lambda fut, i=i: finish(i, fut))
    return out


def infer_tiled(server, image1: np.ndarray, image2: np.ndarray,
                tile_hw: Tuple[int, int] = DEFAULT_TILE_HW,
                overlap: int = DEFAULT_OVERLAP,
                deadline_ms: Optional[float] = None,
                workload: str = "flow",
                timeout: float = 600.0) -> Dict:
    """Blocking form of :func:`submit_tiled`."""
    return submit_tiled(server, image1, image2, tile_hw=tile_hw,
                        overlap=overlap, deadline_ms=deadline_ms,
                        workload=workload).result(timeout=timeout)


def abstract_tiled_forward(iters: int = 2,
                           tile_hw: Tuple[int, int] = (128, 224),
                           batch: int = 2,
                           overrides: Optional[Dict] = None):
    """The tile family's lowerable serving graph — the serve forward at
    the tile's static shape (tiles are ordinary requests of the tile
    bucket family; there is no separate tiled model).  Registered as
    ``tiled_serve_forward`` so the tile executable is audited,
    budgeted and coverage-checked like every family the fleet compiles.
    The audit shape is a reduced tile (/8-divisible, same aspect class
    as :data:`DEFAULT_TILE_HW`) to keep engine compile cost bounded;
    the structure is shape-independent."""
    from raft_tpu.serve.engine import abstract_serve_forward

    return abstract_serve_forward(iters=iters, hw=tuple(tile_hw),
                                  batch=batch, overrides=overrides)
