"""Admission-controlled request queue + deadline-aware dynamic batcher.

The request path's resilience contract (the PR 6/7
recover-or-typed-incident rule, extended to traffic):

- **No silent drops.**  Every submitted request reaches exactly one
  terminal outcome: a result, or a TYPED rejection
  (:class:`QueueFullError`, :class:`DeadlineExceededError`,
  :class:`BadRequestError`) that also lands in the run ledger as an
  incident.  The server's counters prove the conservation law
  (``submitted == served + rejected``) and the chaos overload scenario
  asserts it.
- **Admission control.**  The queue is bounded; a full queue sheds the
  NEW request typed (``queue-full``) instead of growing without bound
  (latency collapse) or silently replacing queued work.  Mis-shaped
  requests (wrong rank/channels, mismatched pair, no bucket family
  holds them) are rejected typed at submit (``bad-request``) — they
  could never be served, so they must not occupy queue capacity.
- **Deadlines.**  A request may carry one; the batcher re-checks it at
  assembly time and rejects already-expired requests typed
  (``deadline-exceeded``) BEFORE dispatch — device time is the scarce
  resource, and spending it computing an answer nobody is waiting for
  is the storm failure mode.
- **Poison isolation.**  Non-finite input pixels are detected per slot
  at batch assembly (off the caller thread — the full-image scan
  overlaps the batch window).  A poisoned request is rejected typed
  (``bad-request``) and its slot stays ZERO — bit-identical to the
  empty-slot padding a smaller batch would have had, so its neighbors'
  outputs are provably identical to a batch the poisoned request never
  joined (tests/test_serve.py pins this bit-exactly).

Batching is shape-bucketed: per-family FIFO lanes (engine.py's static
pad families), one batch per dispatch drawn from the family whose HEAD
request is oldest — global FIFO fairness without mixing shapes into
one executable.
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np


class RequestError(RuntimeError):
    """Typed rejection; ``kind`` is the ledger incident type."""

    kind = "bad-request"


class QueueFullError(RequestError):
    kind = "queue-full"


class DeadlineExceededError(RequestError):
    kind = "deadline-exceeded"


class BadRequestError(RequestError):
    kind = "bad-request"


@dataclass
class Request:
    """One admitted inference request."""

    rid: int
    image1: np.ndarray
    image2: np.ndarray
    family: str
    hw: Tuple[int, int]                  # original (h, w) for unpad
    t_submit: float
    deadline: Optional[float] = None     # absolute monotonic seconds
    stream: Optional[str] = None         # video stream id (warm start)
    # which workload's executable serves this request ("flow",
    # "stereo", ...): requests batch ONLY within one (workload,
    # family) lane — a batch is one executable dispatch
    workload: str = "flow"
    future: Future = field(default_factory=Future)
    # per-request trace context (obs/trace.py Trace) — None when the
    # server runs with tracing off; the batcher never touches it
    trace: Optional[object] = None

    @property
    def lane(self) -> Tuple[str, str]:
        """The batching key: (workload, shape family)."""
        return (self.workload, self.family)


def validate_shape(image1: np.ndarray, image2: np.ndarray,
                   buckets: Dict[str, Tuple[int, int]]) -> str:
    """Admission-time shape validation; returns the bucket family.
    Raises :class:`BadRequestError` (typed) for anything unservable."""
    for name, img in (("image1", image1), ("image2", image2)):
        if not isinstance(img, np.ndarray):
            raise BadRequestError(f"{name} is {type(img).__name__}, "
                                  f"not an ndarray")
        if img.ndim != 3 or img.shape[-1] != 3:
            raise BadRequestError(
                f"{name} has shape {getattr(img, 'shape', None)}; "
                f"expected (H, W, 3)")
        if img.dtype not in (np.float32, np.uint8):
            raise BadRequestError(
                f"{name} dtype {img.dtype} is not float32/uint8")
    if image1.shape != image2.shape:
        raise BadRequestError(
            f"pair shapes disagree: {image1.shape} vs {image2.shape}")
    from raft_tpu.serve.engine import bucket_for

    h, w = image1.shape[:2]
    family = bucket_for(h, w, buckets)
    if family is None:
        raise BadRequestError(
            f"no bucket family holds a {h}x{w} frame (largest: "
            f"{max(buckets.values(), key=lambda s: s[0] * s[1])})")
    return family


def slot_is_finite(req: Request) -> bool:
    """Assembly-time poison check (uint8 cannot be non-finite)."""
    for img in (req.image1, req.image2):
        if img.dtype == np.float32 and not np.isfinite(img).all():
            return False
    return True


class RequestQueue:
    """Bounded, family-laned FIFO with typed admission control.

    Capacity is GLOBAL (a pile-up in one family must still shed load —
    the device is one resource); ordering is per-family FIFO with the
    oldest head winning batch selection.
    """

    def __init__(self, capacity: int,
                 buckets: Dict[str, Tuple[int, int]]):
        if capacity < 1:
            raise ValueError(f"queue capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.buckets = dict(buckets)
        # lanes keyed (workload, family): heterogeneous workloads share
        # the queue's GLOBAL capacity (the device is one resource) but
        # never share a batch (a batch is one executable dispatch)
        self._lanes: Dict[Tuple[str, str], collections.deque] = {}
        self._size = 0
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._ids = itertools.count()
        self._closed = False

    def __len__(self) -> int:
        with self._lock:
            return self._size

    @property
    def depth_fraction(self) -> float:
        """Queue pressure in [0, 1] — the degradation controller's
        primary signal."""
        with self._lock:
            return self._size / self.capacity

    def submit(self, image1: np.ndarray, image2: np.ndarray,
               deadline: Optional[float] = None,
               stream: Optional[str] = None,
               workload: str = "flow",
               clock=time.monotonic) -> Request:
        """Admit a request or raise a typed :class:`RequestError`.

        Shape/bucket validation happens HERE (unservable work must not
        occupy capacity); the finiteness scan happens at assembly, off
        the caller thread.  ``workload`` picks the executable family
        lane (the server validates it against its engine table before
        calling in).
        """
        family = validate_shape(image1, image2, self.buckets)
        req = Request(rid=next(self._ids), image1=image1, image2=image2,
                      family=family, hw=tuple(image1.shape[:2]),
                      t_submit=clock(), deadline=deadline, stream=stream,
                      workload=workload)
        with self._lock:
            if self._closed:
                raise BadRequestError("server is shutting down")
            if self._size >= self.capacity:
                raise QueueFullError(
                    f"queue at capacity ({self.capacity}); shedding "
                    f"request {req.rid} typed instead of queueing "
                    f"unbounded")
            self._lanes.setdefault(req.lane,
                                   collections.deque()).append(req)
            self._size += 1
            self._nonempty.notify()
        return req

    def pop_batch(self, max_batch: int,
                  timeout: Optional[float] = None) -> List[Request]:
        """Up to ``max_batch`` requests from the (workload, family)
        lane whose head is oldest; blocks up to ``timeout`` for work.
        Empty list on timeout or close."""
        with self._lock:
            if not self._size:
                self._nonempty.wait(timeout)
            if not self._size:
                return []
            key = min(
                (k for k, lane in self._lanes.items() if lane),
                key=lambda k: self._lanes[k][0].t_submit)
            lane = self._lanes[key]
            out = []
            while lane and len(out) < max_batch:
                out.append(lane.popleft())
            self._size -= len(out)
            return out

    def pop_lane(self, lane: Tuple[str, str], max_n: int) -> List[Request]:
        """Up to ``max_n`` requests from ONE (workload, family) lane,
        non-blocking — the continuous batcher's admission pop: free
        slots of an in-flight batch can only take requests that match
        its executable (same workload, same shape family)."""
        with self._lock:
            q = self._lanes.get(lane)
            out: List[Request] = []
            while q and len(out) < max_n:
                out.append(q.popleft())
            self._size -= len(out)
            return out

    def other_lane_waiting(self, lane: Tuple[str, str]) -> bool:
        """True when any lane OTHER than ``lane`` has queued work —
        the continuous batcher's fairness signal: while another lane
        waits, the in-flight batch stops admitting same-lane joiners
        and drains, so one busy lane can never starve the rest."""
        with self._lock:
            return any(q and k != lane
                       for k, q in self._lanes.items())

    def drain(self) -> List[Request]:
        """Close the queue and return everything still queued (the
        server rejects them typed at shutdown — no silent drops)."""
        with self._lock:
            self._closed = True
            out = [r for lane in self._lanes.values() for r in lane]
            self._lanes.clear()
            self._size = 0
            self._nonempty.notify_all()
            return out


def assemble_batch(reqs: List[Request], hw: Tuple[int, int],
                   batch_size: int, clock=time.monotonic):
    """Build the padded device batch from admitted requests.

    Per-slot gauntlet, in order: deadline (already expired -> typed
    ``deadline-exceeded``, pre-dispatch), poison (non-finite pixels ->
    typed ``bad-request``).  Rejected/empty slots stay zero — the
    bit-identical-neighbors guarantee.

    Returns ``(img1, img2, kept, rejected)``: device-ready float32
    arrays of shape (batch_size, H, W, 3), the per-slot kept requests
    (index-aligned; None for empty/rejected slots), and
    ``(request, RequestError)`` pairs for the typed rejections.
    """
    H, W = hw
    img1 = np.zeros((batch_size, H, W, 3), np.float32)
    img2 = np.zeros((batch_size, H, W, 3), np.float32)
    kept: List[Optional[Request]] = [None] * batch_size
    rejected: List[Tuple[Request, RequestError]] = []
    now = clock()
    slot = 0
    for req in reqs:
        if req.deadline is not None and now > req.deadline:
            rejected.append((req, DeadlineExceededError(
                f"request {req.rid} expired {now - req.deadline:.3f}s "
                f"before dispatch (deadline-aware shed: device time is "
                f"not spent on an answer nobody is waiting for)")))
            continue
        if not slot_is_finite(req):
            rejected.append((req, BadRequestError(
                f"request {req.rid} carries non-finite input pixels; "
                f"rejected per-slot — its batch slot stays zero, so "
                f"neighbors' outputs are unaffected")))
            continue
        from raft_tpu.serve.engine import pad_to_bucket

        img1[slot] = pad_to_bucket(req.image1.astype(np.float32), hw)
        img2[slot] = pad_to_bucket(req.image2.astype(np.float32), hw)
        kept[slot] = req
        slot += 1
    return img1, img2, kept, rejected
