"""Dispatch watchdog: a wedged compile/dispatch becomes a typed
``serve-stalled`` incident and a loud nonzero exit, never a silent hang.

The PR 7 collective-watchdog pattern applied to the request path: the
batcher thread brackets every potentially-wedging operation (XLA
compile at warmup, device dispatch per batch) with
``begin(detail)``/``done()``; a daemon thread checks that no bracket
has been open longer than the bound.  Before the first completed
dispatch the bound is ``startup_factor x timeout`` — warmup compiles
legitimately take many step-times, but a wedged compiler must still
kill the server within a configured window instead of hanging the
deployment's readiness probe forever.

A trip writes the typed incident through ``on_incident``, runs the
``on_trip`` flush hook, and ``os._exit``\\ s with
:data:`SERVE_WATCHDOG_EXIT_CODE` — the batcher's main line is blocked
inside native code, so no Python-level unwind can reach it.  The exit
code is distinct from the pod watchdog's 13 so chaos matrices can tell
the two verdicts apart.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from raft_tpu.resilience import exit_codes

# The integer lives in resilience/exit_codes.py (the typed registry);
# this name stays as the historical import surface (tests, serve CLI,
# chaos matrix).
SERVE_WATCHDOG_EXIT_CODE = exit_codes.SERVE_WATCHDOG_EXIT_CODE

STARTUP_TIMEOUT_FACTOR = 10


class DispatchWatchdog:
    """Monitors bracketed serve-side operations for wedges."""

    def __init__(self, timeout_s: float,
                 on_incident: Callable[[str, str], None],
                 on_trip: Optional[Callable[[str], None]] = None,
                 startup_factor: float = STARTUP_TIMEOUT_FACTOR,
                 interval: Optional[float] = None,
                 exit_fn: Callable[[int], None] = os._exit,
                 clock=time.monotonic):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.startup_factor = float(startup_factor)
        self.interval = (max(0.1, min(2.0, self.timeout_s / 4.0))
                         if interval is None else float(interval))
        self._on_incident = on_incident
        self._on_trip = on_trip
        self._exit = exit_fn
        self._clock = clock
        self._lock = threading.Lock()
        # token -> (detail, t0, slow): brackets may OVERLAP (the
        # caller-thread warmup compile races the batcher thread's first
        # dispatch), so a single slot would let begin/done pairs
        # clobber each other and leave a genuinely wedged operation
        # unmonitored
        self._open: dict = {}
        self._next_token = 0
        self._completed = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.tripped: Optional[str] = None

    # -- batcher-side brackets ----------------------------------------------

    def begin(self, detail: str, slow: bool = False) -> int:
        """Open a bracket; returns the token ``done`` takes.
        ``slow=True`` grants this bracket the startup-factor bound
        even in steady state — the lazily-compiled-executable case (a
        legitimate multi-second XLA compile mid-serve must not be
        declared a wedge by the dispatch-sized timeout)."""
        with self._lock:
            token = self._next_token
            self._next_token += 1
            self._open[token] = (detail, self._clock(), bool(slow))
            return token

    def done(self, token: int) -> None:
        with self._lock:
            self._open.pop(token, None)
            self._completed += 1

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serve-watchdog")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval * 4)
            self._thread = None

    # -- thread body ---------------------------------------------------------

    def check(self) -> Optional[str]:
        """One stall check (exposed for deterministic tests); returns
        the stall detail when the bound is exceeded, else None."""
        with self._lock:
            open_brackets = list(self._open.values())
            completed = self._completed
        now = self._clock()
        for detail, t0, slow in open_brackets:
            wide = slow or not completed
            bound = self.timeout_s * (self.startup_factor if wide
                                      else 1.0)
            stalled = now - t0
            if stalled <= bound:
                continue
            phase = ("startup/compile (bound is "
                     f"{self.startup_factor:.0f}x the timeout)" if wide
                     else f"steady state ({completed} dispatches "
                          f"completed)")
            return (f"no progress on [{detail}] for {stalled:.1f}s (> "
                    f"{bound:.1f}s) in {phase} — compile or dispatch "
                    f"wedged; terminating loudly instead of hanging "
                    f"the deployment")
        return None

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            verdict = self.check()
            if verdict is None:
                continue
            self.tripped = "serve-stalled"
            try:
                self._on_incident("serve-stalled", verdict)
                if self._on_trip is not None:
                    self._on_trip("serve-stalled")
            finally:
                self._exit(SERVE_WATCHDOG_EXIT_CODE)
            return
