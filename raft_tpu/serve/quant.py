"""Int8 serving: quantized weights + int8 corr contraction, certified.

The serve forward's cost is encoder + update-block matmuls; this module
quantizes BOTH halves for the serving path only (training never sees
any of it):

- **Weights**: every conv kernel under ``params/fnet``, ``params/cnet``
  and ``params/refine/update_block`` is replaced by a
  :class:`QTensor` — int8 codes plus a per-tensor symmetric f32 scale
  (``scale = amax/127``, codes clamped before the int8 cast so the
  conversion can never wrap).  Dequantization happens IN-GRAPH
  (``codes.astype(f32) * scale`` — the scale re-applies before any
  nonlinearity or residual add, the requant-hygiene order engine 7
  checks), so ``model.apply`` sees an ordinary variables tree and the
  model code is untouched.  Biases / norm parameters stay f32.
- **The corr-volume contraction**: ``RAFTConfig.quantized_serve``
  routes the pyramid through ``ops.corr.build_corr_pyramid_q8`` —
  fmaps quantize at the static calibrated ``q8_clip``, each level
  contracts i8·i8→i32 on the MXU (the narrow-accum contract), and the
  observed fmap magnitude is sown into the ``'quant'`` collection.

**The fallback contract** (the certifier's runtime half): graftlint
engine 7 (``analysis/quant_audit.py``) proves the quantize sites safe
under the declared input spec; at runtime the graph itself emits an
``oob`` flag — the input premise (|pixels| <= ``IMG_PREMISE_MAX``) or
the fmap calibration premise (|fmap| <= ``q8_clip``) failed for this
batch.  :class:`QuantServeEngine` checks the flag on the host and, when
it fires, emits a typed ``serve-quant-fallback`` incident and re-runs
the batch on the bf16 executable it keeps warm — degraded TYPED, never
silently serving bad flow (the chaos ``serve-quant-overflow`` row
drives exactly this path end to end).

``abstract_serve_forward_q8`` is the lowerable entry behind the
``serve_forward_q8``/``serve_forward_q8_warm`` registry records —
exactly the graph :class:`QuantServeEngine` compiles, audited by all
eight engines.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, Optional, Tuple

import numpy as np

from raft_tpu.serve.engine import ServeEngine, serve_config

logger = logging.getLogger(__name__)

# The certifier's declared input premise: serve images are decoded
# uint8 pixels in [0, 255]; 4x headroom tolerates mild preprocessing
# drift without tripping, anything past it voids the range proof.
IMG_PREMISE_MAX = 1024.0

# Param subtrees whose conv kernels quantize (the serve-cost carriers:
# feature/context encoders + the per-iteration update block).  Matched
# against pytree key paths; everything else (biases, norm scales/means,
# flow-head convs' biases, batch_stats) stays f32.
QUANT_SCOPES = ("fnet", "cnet", "update_block")


@dataclasses.dataclass(frozen=True)
class QTensor:
    """A quantized parameter leaf: int8 codes + per-tensor f32 scale.

    Registered as a pytree WITH KEYS so cache-key tree signatures and
    the audits' keypath-based range recipes see ``.q`` / ``.scale``
    leaves by name.
    """

    q: Any
    scale: Any

    def tree_flatten_with_keys(self):
        import jax

        return (((jax.tree_util.GetAttrKey("q"), self.q),
                 (jax.tree_util.GetAttrKey("scale"), self.scale)), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)


def _register_qtensor():
    import jax

    try:
        jax.tree_util.register_pytree_with_keys_class(QTensor)
    except ValueError:
        pass  # already registered (repeated import paths)


_REGISTERED = False


def _ensure_registered():
    global _REGISTERED
    if not _REGISTERED:
        _register_qtensor()
        _REGISTERED = True


def _is_quant_path(path) -> bool:
    keys = [getattr(k, "key", getattr(k, "name", None))
            for k in path]
    return (len(keys) > 0 and keys[-1] == "kernel"
            and any(k in QUANT_SCOPES for k in keys if isinstance(k, str)))


def quantize_variables(variables):
    """Host-side: replace the quantizable kernels with QTensor leaves.

    Symmetric per-tensor scale ``amax/127`` (floored so an all-zero
    kernel still round-trips); codes clamp to [-127, 127] before the
    int8 cast — the cast can never wrap, which is the structural
    guarantee engine 7's range-overflow rule checks on the abstract
    graph.
    """
    import jax
    import jax.numpy as jnp

    _ensure_registered()

    def q(path, leaf):
        if not _is_quant_path(path):
            return leaf
        x = np.asarray(leaf, np.float32)
        scale = max(float(np.abs(x).max()) / 127.0, 1e-8)
        codes = np.clip(np.round(x / scale), -127, 127).astype(np.int8)
        return QTensor(jnp.asarray(codes), jnp.float32(scale))

    return jax.tree_util.tree_map_with_path(q, variables)


def quantize_abstract(variables_sds):
    """The ShapeDtypeStruct image of :func:`quantize_variables` — the
    registry builders construct the audited graph without weights."""
    import jax
    import jax.numpy as jnp

    _ensure_registered()

    def q(path, leaf):
        if not _is_quant_path(path):
            return leaf
        return QTensor(jax.ShapeDtypeStruct(leaf.shape, jnp.int8),
                       jax.ShapeDtypeStruct((), jnp.float32))

    return jax.tree_util.tree_map_with_path(q, variables_sds)


def dequantize_variables(qvars, dtype=None):
    """In-graph: QTensor leaves back to float kernels (scale re-applies
    HERE, before the kernel reaches any conv — requant hygiene)."""
    import jax
    import jax.numpy as jnp

    _ensure_registered()
    dt = dtype or jnp.float32

    def dq(leaf):
        if isinstance(leaf, QTensor):
            return leaf.q.astype(dt) * leaf.scale.astype(dt)
        return leaf

    return jax.tree_util.tree_map(
        dq, qvars, is_leaf=lambda x: isinstance(x, QTensor))


def q8_model(model):
    """The int8-corr twin of a serving model: same params, same
    architecture, ``quantized_serve=True`` corr path."""
    cfg = dataclasses.replace(model.cfg, quantized_serve=True)
    return type(model)(cfg)


def make_q8_forward(model, iters: int, warm: bool):
    """THE jitted int8 test_mode forward (cold / warm-start): the graph
    the engines audit and :class:`QuantServeEngine` compiles.

    Returns ``(flow_low, flow_up, oob)`` with ``oob`` an f32 scalar
    (0.0/1.0 — workload outputs are a declared-f32 boundary): 1.0 means
    a certifier premise failed at runtime (input pixels past
    ``IMG_PREMISE_MAX`` or fmap magnitude past the calibrated clip)
    and the caller must fall back to the bf16 executable.
    """
    import jax
    import jax.numpy as jnp

    clip = jnp.float32(model.cfg.q8_clip)

    def run(qv, a, b, f=None):
        v = dequantize_variables(qv)
        kw = {} if f is None else {"flow_init": f}
        (flow_low, flow_up), mods = model.apply(
            v, a, b, iters=iters, test_mode=True, mutable=["quant"],
            **kw)
        fmap_amax = mods["quant"]["fmap_amax"][0]
        img_amax = jnp.maximum(jnp.max(jnp.abs(a)), jnp.max(jnp.abs(b)))
        oob = jnp.maximum(
            (fmap_amax > clip).astype(jnp.float32),
            (img_amax > jnp.float32(IMG_PREMISE_MAX))
            .astype(jnp.float32))
        return flow_low, flow_up, oob

    if warm:
        return jax.jit(lambda qv, a, b, f: run(qv, a, b, f))
    return jax.jit(lambda qv, a, b: run(qv, a, b))


def compile_q8_forward(model, variables, img1_sds, img2_sds,
                       iters: int, flow_sds=None):
    """lower → compile :func:`make_q8_forward` — the AOT build recipe
    behind every ``serve_forward_q8`` executable (``variables`` is the
    QTensor tree)."""
    fn = make_q8_forward(model, iters, warm=flow_sds is not None)
    if flow_sds is not None:
        return fn.lower(variables, img1_sds, img2_sds,
                        flow_sds).compile()
    return fn.lower(variables, img1_sds, img2_sds).compile()


def abstract_serve_forward_q8(iters: int = 2,
                              hw: Tuple[int, int] = (64, 64),
                              batch: int = 2, warm: bool = False,
                              overrides: Optional[Dict] = None):
    """The int8 serving forward over abstract inputs: the lowerable
    entry point behind ``serve_forward_q8``/``serve_forward_q8_warm``
    in ``raft_tpu/entrypoints.py`` (exactly the graph
    :class:`QuantServeEngine` compiles, built without weights).

    Returns ``(fwd, args_sds)`` with args ``(qvars, img1, img2[,
    flow_init])`` — qvars is the variables tree with QTensor (int8
    codes + f32 scale) kernel leaves.
    """
    import jax
    import jax.numpy as jnp

    from raft_tpu.models import RAFT

    model = RAFT(serve_config(overrides=dict(overrides or {},
                                             quantized_serve=True)))
    H, W = hw
    img_sds = jax.ShapeDtypeStruct((batch, H, W, 3), jnp.float32)
    variables_sds = dict(jax.eval_shape(
        lambda rng, a, b: model.init(rng, a, b, iters=iters, train=True),
        jax.random.PRNGKey(0), img_sds, img_sds))
    # init under quantized_serve also sows the 'quant' collection —
    # it is an OUTPUT of apply(mutable=...), not an input
    variables_sds.pop("quant", None)
    qvars_sds = quantize_abstract(variables_sds)
    fwd = make_q8_forward(model, iters, warm=warm)
    if warm:
        flow_sds = jax.ShapeDtypeStruct((batch, H // 8, W // 8, 2),
                                        jnp.float32)
        return fwd, (qvars_sds, img_sds, img_sds, flow_sds)
    return fwd, (qvars_sds, img_sds, img_sds)


class QuantServeEngine(ServeEngine):
    """The int8 serving executor with the typed bf16 fallback.

    Holds TWO executables per (family, iters, warm): the q8 one it
    serves from, and the bf16 one it falls back to when the graph's
    ``oob`` tripwire reports a violated calibration premise.  The
    fallback emits a ``serve-quant-fallback`` incident through
    ``on_incident`` (ledger-typed; the chaos row and the summary
    counters read it) and re-runs the SAME batch on the bf16
    executable — the request is always served, never silently wrong.

    Canary coverage: FlowServer's golden-input canary stores a
    reference to THIS engine per (workload, family), so its periodic
    probe exercises the q8 executable and tripwire; ``invalidate``
    evicts both twins so a canary recompile-and-recheck rebuilds the
    pair coherently.
    """

    def __init__(self, model, variables, batch_size: int = 4,
                 aot_cache=None, spans=None,
                 cache_tag: str = "serve_forward_q8",
                 warm_channels: int = 2, on_incident=None):
        _ensure_registered()
        qm = q8_model(model)
        qvars = quantize_variables(variables)
        super().__init__(qm, qvars, batch_size=batch_size,
                         aot_cache=aot_cache, spans=spans,
                         compile_fn=compile_q8_forward,
                         cache_tag=cache_tag,
                         warm_channels=warm_channels)
        self.on_incident = on_incident
        self.fallback = ServeEngine(model, variables,
                                    batch_size=batch_size,
                                    aot_cache=aot_cache, spans=spans,
                                    warm_channels=warm_channels)
        self.fallbacks = 0

    def warmup(self, families, iters_levels, warm_too: bool = True
               ) -> float:
        # warm BOTH twins: a fallback mid-dispatch must never pay a
        # compile inside the watchdog bracket
        t = super().warmup(families, iters_levels, warm_too=warm_too)
        return t + self.fallback.warmup(families, iters_levels,
                                        warm_too=warm_too)

    def invalidate(self, hw, iters, warm: bool = False) -> bool:
        a = super().invalidate(hw, iters, warm=warm)
        b = self.fallback.invalidate(hw, iters, warm=warm)
        return a or b

    def forward(self, hw, iters, img1, img2, flow_init=None):
        warm = flow_init is not None
        fn = self.executable(hw, iters, warm=warm)
        with self.spans.span("dispatch"):
            if warm:
                flow_low, flow_up, oob = fn(self.variables, img1, img2,
                                            flow_init)
            else:
                flow_low, flow_up, oob = fn(self.variables, img1, img2)
            tripped = float(np.asarray(oob)) > 0.0
            if not tripped:
                return np.asarray(flow_low), np.asarray(flow_up)
        # premise violated: typed incident + the SAME batch on bf16
        self.fallbacks += 1
        detail = (f"q8 range tripwire fired (hw={tuple(hw)} "
                  f"iters={iters} warm={warm}): input or fmap "
                  f"magnitude left the calibrated range — serving "
                  f"this batch on the bf16 executable")
        logger.warning("serve-quant-fallback: %s", detail)
        if self.on_incident is not None:
            self.on_incident("serve-quant-fallback", detail)
        return self.fallback.forward(hw, iters, img1, img2,
                                     flow_init=flow_init)
