"""raft_tpu.serve: the fault-tolerant serving subsystem.

Pieces (one module each, composable and individually testable):

- :mod:`~raft_tpu.serve.aot` — crash-safe on-disk cache of AOT-compiled
  executables (manifest-verified, typed ``serve-cache-corrupt``
  fallback to recompile);
- :mod:`~raft_tpu.serve.engine` — bucketed bf16 inference executor +
  the ``abstract_serve_forward`` entry point the graftlint engines
  audit;
- :mod:`~raft_tpu.serve.batcher` — bounded queue, typed admission
  control, deadline-aware assembly, per-slot poison isolation;
- :mod:`~raft_tpu.serve.degrade` — the adaptive refinement-iteration
  controller (graceful degradation) + latency tracking;
- :mod:`~raft_tpu.serve.watchdog` — wedged compile/dispatch -> typed
  ``serve-stalled`` + nonzero exit;
- :mod:`~raft_tpu.serve.server` — the FlowServer composition with
  health/readiness probes, the obs-ledger serving summary, and
  continuous batching (iteration-boundary admission into in-flight
  batch slots);
- :mod:`~raft_tpu.serve.router` — consistent-hash stream-affinity
  routing over a PodChannel-backed membership/health view;
- :mod:`~raft_tpu.serve.fleet` — the FleetServer front door: N
  replicas, warm-state spill store, typed rescue on replica death,
  zero-downtime rolling restarts;
- :mod:`~raft_tpu.serve.tiled` — tiled high-res (4K) inference:
  overlap-blend seams over tiles fed through the bucketed batcher.

``python -m raft_tpu.serve`` drives a synthetic load session (the
chaos-matrix and bench harness target); see ``--help``.
"""

from raft_tpu.serve.aot import AOTCache, cache_key, env_fingerprint
from raft_tpu.serve.batcher import (BadRequestError, DeadlineExceededError,
                                    QueueFullError, Request, RequestError,
                                    RequestQueue)
from raft_tpu.serve.degrade import (DEFAULT_ITER_LEVELS, IterationController,
                                    LatencyTracker)
from raft_tpu.serve.engine import (ServeEngine, abstract_serve_forward,
                                   bucket_for, default_buckets,
                                   pad_to_bucket, serve_config)
from raft_tpu.serve.fleet import FleetServer, SpillStore
from raft_tpu.serve.router import (FleetMembership, FleetRouter, HashRing,
                                   LocalKVStore, NoReplicaError)
from raft_tpu.serve.server import FlowServer
from raft_tpu.serve.watchdog import (SERVE_WATCHDOG_EXIT_CODE,
                                     DispatchWatchdog)

__all__ = [
    "FleetServer", "SpillStore",
    "FleetMembership", "FleetRouter", "HashRing", "LocalKVStore",
    "NoReplicaError",
    "AOTCache", "cache_key", "env_fingerprint",
    "BadRequestError", "DeadlineExceededError", "QueueFullError",
    "Request", "RequestError", "RequestQueue",
    "DEFAULT_ITER_LEVELS", "IterationController", "LatencyTracker",
    "ServeEngine", "abstract_serve_forward", "bucket_for",
    "default_buckets", "pad_to_bucket", "serve_config",
    "FlowServer",
    "SERVE_WATCHDOG_EXIT_CODE", "DispatchWatchdog",
]
