"""raft_tpu.serve: the fault-tolerant serving subsystem.

Pieces (one module each, composable and individually testable):

- :mod:`~raft_tpu.serve.aot` — crash-safe on-disk cache of AOT-compiled
  executables (manifest-verified, typed ``serve-cache-corrupt``
  fallback to recompile);
- :mod:`~raft_tpu.serve.engine` — bucketed bf16 inference executor +
  the ``abstract_serve_forward`` entry point the graftlint engines
  audit;
- :mod:`~raft_tpu.serve.batcher` — bounded queue, typed admission
  control, deadline-aware assembly, per-slot poison isolation;
- :mod:`~raft_tpu.serve.degrade` — the adaptive refinement-iteration
  controller (graceful degradation) + latency tracking;
- :mod:`~raft_tpu.serve.watchdog` — wedged compile/dispatch -> typed
  ``serve-stalled`` + nonzero exit;
- :mod:`~raft_tpu.serve.server` — the FlowServer composition with
  health/readiness probes and the obs-ledger serving summary.

``python -m raft_tpu.serve`` drives a synthetic load session (the
chaos-matrix and bench harness target); see ``--help``.
"""

from raft_tpu.serve.aot import AOTCache, cache_key, env_fingerprint
from raft_tpu.serve.batcher import (BadRequestError, DeadlineExceededError,
                                    QueueFullError, Request, RequestError,
                                    RequestQueue)
from raft_tpu.serve.degrade import (DEFAULT_ITER_LEVELS, IterationController,
                                    LatencyTracker)
from raft_tpu.serve.engine import (ServeEngine, abstract_serve_forward,
                                   bucket_for, default_buckets,
                                   pad_to_bucket, serve_config)
from raft_tpu.serve.server import FlowServer
from raft_tpu.serve.watchdog import (SERVE_WATCHDOG_EXIT_CODE,
                                     DispatchWatchdog)

__all__ = [
    "AOTCache", "cache_key", "env_fingerprint",
    "BadRequestError", "DeadlineExceededError", "QueueFullError",
    "Request", "RequestError", "RequestQueue",
    "DEFAULT_ITER_LEVELS", "IterationController", "LatencyTracker",
    "ServeEngine", "abstract_serve_forward", "bucket_for",
    "default_buckets", "pad_to_bucket", "serve_config",
    "FlowServer",
    "SERVE_WATCHDOG_EXIT_CODE", "DispatchWatchdog",
]
