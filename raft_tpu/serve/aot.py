"""Crash-safe on-disk cache of AOT-compiled XLA executables.

The serving cold-start problem: ``jax.jit`` compiles lazily, per
process, so every server restart (and every eval/demo CLI invocation)
re-pays seconds-to-minutes of XLA compile before the first request is
served.  ``jax.experimental.serialize_executable`` can round-trip a
compiled executable through bytes; this module turns that into a cache
with the PR 6 checkpoint-manifest discipline:

- every store is an **atomic** fsync'd-tmp + rename
  (``training/state.py`` machinery) and ships a sidecar manifest
  (``<key>.aotx.manifest.json``: byte size, sha256 of the exact bytes
  renamed, the environment fingerprint, a human-readable label) —
  written AFTER the blob, so a kill between the renames leaves a blob
  with no manifest (an unverifiable file, refused at load), never a
  manifest describing bytes that don't exist;
- every load **verifies before trusting**: size + sha256 against the
  manifest catches torn/truncated/bit-rotted files WITHOUT unpickling
  attacker-grade bytes, and the environment fingerprint (jax/jaxlib
  version, backend platform, device kind) catches a cache directory
  carried across an upgrade — a stale executable must never be fed
  inputs it was not compiled for;
- a failed verification is a typed ``serve-cache-corrupt`` incident and
  a **fallback to recompile** — a torn cache file must never crash the
  server or silently mis-serve, it only costs the cold compile it would
  have saved.

Cache keys are content-addressed (sha256 over the caller's key parts:
config fingerprint, weight-tree signature, input shapes/dtypes,
iteration count), so distinct graphs can never collide and a config
change naturally misses instead of mis-serving.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import time
from typing import Callable, Dict, Optional, Tuple

logger = logging.getLogger(__name__)

AOT_SUFFIX = ".aotx"
AOT_MANIFEST_VERSION = 1

# Incident type for a cache entry that failed verification or
# deserialization (taxonomy: obs/events.py) — severity "recovered":
# the fallback recompile restores service.
CACHE_CORRUPT_INCIDENT = "serve-cache-corrupt"


def env_fingerprint() -> str:
    """Fingerprint of everything a serialized executable is specific to:
    jax/jaxlib versions and the backend's platform + device kind.  An
    executable deserialized under a different environment may crash or —
    worse — mis-execute; a mismatch is a cache MISS, not corruption."""
    import jax
    import jaxlib

    dev = jax.devices()[0]
    return "|".join([jax.__version__, jaxlib.__version__,
                     dev.platform, getattr(dev, "device_kind", "?")])


def cache_key(*parts) -> str:
    """Content-addressed key: sha256 over the reprs of ``parts``."""
    blob = "\x1e".join(repr(p) for p in parts)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:32]


class AOTCache:
    """Disk cache of serialized compiled executables, verify-on-load.

    ``on_incident(kind, detail)`` receives the typed
    ``serve-cache-corrupt`` firing when a cached entry fails
    verification; the entry is quarantined (renamed ``.corrupt``) so
    the next load doesn't re-pay the failed verify, and the caller
    recompiles.  ``stats`` counts hits/misses/corruptions and the
    wall seconds spent compiling vs loading — the cold-vs-warm startup
    numbers the serving CLI and eval harness log.
    """

    def __init__(self, cache_dir: str,
                 on_incident: Optional[Callable[[str, str], None]] = None):
        self.cache_dir = cache_dir
        self._on_incident = on_incident
        self._env = None  # lazy: importing jax at construction is not free
        self.stats: Dict[str, float] = {
            "hits": 0, "misses": 0, "corrupt": 0,
            "compile_s": 0.0, "load_s": 0.0,
        }
        os.makedirs(cache_dir, exist_ok=True)

    # -- paths ---------------------------------------------------------------

    def path(self, key: str) -> str:
        return os.path.join(self.cache_dir, key + AOT_SUFFIX)

    def _manifest_path(self, key: str) -> str:
        from raft_tpu.training.state import manifest_path

        return manifest_path(self.path(key))

    def _env_fp(self) -> str:
        if self._env is None:
            self._env = env_fingerprint()
        return self._env

    def _incident(self, detail: str) -> None:
        self.stats["corrupt"] += 1
        logger.warning("AOT cache: %s", detail)
        if self._on_incident is not None:
            self._on_incident(CACHE_CORRUPT_INCIDENT, detail)

    def _quarantine(self, key: str) -> None:
        """Move a failed entry aside so the NEXT load is a clean miss
        instead of re-verifying known-bad bytes; best-effort."""
        for p in (self.path(key), self._manifest_path(key)):
            try:
                if os.path.exists(p):
                    os.replace(p, p + ".corrupt")
            except OSError:
                logger.warning("AOT cache: could not quarantine %s", p)

    # -- load / store --------------------------------------------------------

    def load(self, key: str, label: str = ""):
        """The cached executable for ``key``, or None.

        Missing entry or environment mismatch -> miss (None, silent).
        Present-but-unverifiable entry (torn blob, sha mismatch, missing
        or unreadable manifest, undeserializable bytes) -> typed
        ``serve-cache-corrupt`` incident, quarantine, None.
        """
        path = self.path(key)
        if not os.path.exists(path):
            return None
        t0 = time.perf_counter()
        mpath = self._manifest_path(key)
        try:
            with open(mpath, encoding="utf-8") as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            # a blob with no (readable) manifest is unverifiable: the
            # kill-between-renames shape, or a torn manifest write
            self._incident(
                f"cache entry {key} ({label or 'unlabeled'}) has no "
                f"verifiable manifest ({type(e).__name__}: {e}); "
                f"recompiling instead of trusting unverified bytes")
            self._quarantine(key)
            return None
        if manifest.get("env") != self._env_fp():
            # stale cache from another jax/backend: a legitimate miss
            logger.info("AOT cache: %s compiled under %r, this process "
                        "is %r — recompiling", key, manifest.get("env"),
                        self._env_fp())
            return None
        try:
            size = os.path.getsize(path)
            if manifest.get("size") != size:
                raise ValueError(
                    f"size mismatch: manifest says {manifest.get('size')} "
                    f"bytes, file has {size} — torn or truncated write")
            with open(path, "rb") as f:
                data = f.read()
            digest = hashlib.sha256(data).hexdigest()
            if digest != manifest.get("sha256"):
                raise ValueError("sha256 mismatch — content corrupted "
                                 "at rest")
            # bytes proven to be the bytes we wrote; now they may be
            # unpickled/deserialized
            from jax.experimental import serialize_executable as se

            blob, in_tree, out_tree = pickle.loads(data)
            compiled = se.deserialize_and_load(blob, in_tree, out_tree)
        except Exception as e:  # noqa: BLE001 — any failure in the
            # verify/deserialize chain means the entry cannot be
            # trusted; the typed fallback (recompile) is the contract
            self._incident(
                f"cache entry {key} ({label or 'unlabeled'}) failed "
                f"verification ({type(e).__name__}: {e}); falling back "
                f"to recompile")
            self._quarantine(key)
            return None
        self.stats["hits"] += 1
        self.stats["load_s"] += time.perf_counter() - t0
        return compiled

    def store(self, key: str, compiled, label: str = "") -> bool:
        """Serialize ``compiled`` under ``key`` (atomic, manifest
        second).  Returns False (and logs) when the executable does not
        serialize on this backend — callers keep the in-memory copy
        either way."""
        from raft_tpu.training.state import _atomic_write_bytes

        try:
            from jax.experimental import serialize_executable as se

            blob, in_tree, out_tree = se.serialize(compiled)
            data = pickle.dumps((blob, in_tree, out_tree))
        except Exception as e:  # noqa: BLE001 — serialization support
            # is backend-dependent; an unserializable executable only
            # costs the warm start, never the request
            logger.warning("AOT cache: executable %s (%s) does not "
                           "serialize here (%s: %s); serving from the "
                           "in-memory copy only", key, label,
                           type(e).__name__, e)
            return False
        manifest = {
            "v": AOT_MANIFEST_VERSION,
            "label": label,
            "size": len(data),
            "sha256": hashlib.sha256(data).hexdigest(),
            "env": self._env_fp(),
            "created": time.time(),
        }
        try:
            _atomic_write_bytes(self.path(key), data)
            _atomic_write_bytes(
                self._manifest_path(key),
                json.dumps(manifest, sort_keys=True).encode("utf-8"))
        except OSError as e:
            # full disk / read-only cache dir: the compiled executable
            # is in hand — cache problems cost the warm start, never
            # the request (a partial blob left behind is unverifiable
            # and will be rejected+quarantined at the next load)
            logger.warning("AOT cache: could not persist %s (%s): "
                           "%s: %s; serving from the in-memory copy",
                           key, label, type(e).__name__, e)
            return False
        return True

    def get_or_compile(self, key: str, build: Callable[[], object],
                       label: str = "") -> Tuple[object, bool]:
        """The executable for ``key``: loaded warm from disk when a
        verified entry exists, else built via ``build()`` (the XLA
        compile) and stored.  Returns ``(compiled, warm)``."""
        compiled = self.load(key, label=label)
        if compiled is not None:
            logger.info("AOT cache: warm hit for %s (%s)", key, label)
            return compiled, True
        self.stats["misses"] += 1
        t0 = time.perf_counter()
        compiled = build()
        self.stats["compile_s"] += time.perf_counter() - t0
        self.store(key, compiled, label=label)
        return compiled, False
