"""The serving fleet: N FlowServer replicas behind one front door.

PR 10's FlowServer is a single-process story (one queue, one batcher,
one warm-state LRU, one AOT cache); millions of concurrent video
streams need a FLEET.  This module is the composition layer that turns
N replicas into one service without giving up any of the single-server
guarantees:

- **Stream-affinity routing** (router.py): streams ride a consistent-
  hash ring over the live membership view (PR 7's PodChannel as the
  health transport), so a stream's ``flow_init`` warm-start chain keeps
  landing where its state lives, and a replica death moves only that
  replica's streams.
- **Warm-state spill** (:class:`SpillStore`): every served stream frame
  writes its low-res state through a shared on-disk store under the
  PR 6 manifest discipline (atomic fsync'd-tmp+rename, sha256 sidecar,
  verify-before-trust).  A rerouted stream's new replica ADOPTS the
  verified state (typed ``fleet-warm-adopt``) or re-cold-starts typed
  (``fleet-cold-start``) — never an error, never a silent drop of the
  warm chain.
- **Typed rescue**: killing a replica returns its queued requests to
  the front door, which re-places each on a surviving replica
  (``fleet-reroute``); fleet-wide request conservation —
  ``submitted == served + typed rejects + in-flight`` — is a structural
  invariant with its own FATAL ``fleet-conservation`` incident, exactly
  the single-server contract lifted one level.
- **Zero-downtime rolling restart** (:meth:`FleetServer.
  rolling_restart`): drain -> close -> rebuild -> warm AOT restore
  (the shared executable cache makes the restart measurably cheaper
  than the cold start — the warm/cold ratio is recorded per restart),
  one replica at a time, while the rest keep serving.

The replicas here are in-process FlowServers (each with its own
batcher thread) — the CPU test/bench/chaos shape.  The same
composition runs replicas-as-hosts by backing the membership channel
with the real jax.distributed KV client and pointing the spill store
and AOT cache at shared storage; nothing in this module assumes a
shared address space beyond the replica handle's ``submit``/``kill``/
``close`` surface.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import logging
import os
import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from raft_tpu.serve.batcher import (BadRequestError, DeadlineExceededError,
                                    QueueFullError, RequestError)
from raft_tpu.serve.degrade import LatencyTracker
from raft_tpu.serve.router import (FleetMembership, FleetRouter,
                                   LocalKVStore, NoReplicaError,
                                   ReplicaHeartbeat, fleet_channel)
from raft_tpu.serve.server import INCIDENT_SAMPLE

logger = logging.getLogger(__name__)

SPILL_SUFFIX = ".state"
SPILL_MANIFEST_VERSION = 1


class ReplicaLostError(RequestError):
    """A replica died with this request still queued.  Internal to the
    fleet: the front door's completion callback converts it into a
    re-placement on a survivor (the typed rescue), so a caller only
    ever sees it if every survivor also rejects."""

    kind = "fleet-replica-lost"


class SpillStore:
    """Shared on-disk warm-state store, verify-on-load.

    One entry per (workload, stream) key: the stream's latest low-res
    state (``flow_low`` / ``disp_low``), serialized as ``.npy`` bytes
    with the PR 6 manifest discipline — atomic write, sidecar manifest
    (size + sha256 + shape/dtype), blob before manifest.  ``get``
    verifies BEFORE deserializing; a torn/flipped/manifest-less entry
    fires a typed ``fleet-cold-start`` through ``on_incident``, is
    quarantined, and returns None — the stream re-cold-starts, the
    request is still served.  A missing key is a silent None (every
    new stream is legitimately cold)."""

    def __init__(self, store_dir: str,
                 on_incident: Optional[Callable[[str, str], None]] = None):
        self.store_dir = store_dir
        self._on_incident = on_incident
        self.stats: Dict[str, int] = {"puts": 0, "hits": 0, "misses": 0,
                                      "corrupt": 0}
        os.makedirs(store_dir, exist_ok=True)

    def path(self, key: Tuple[str, str]) -> str:
        name = hashlib.sha256(
            f"{key[0]}/{key[1]}".encode("utf-8")).hexdigest()[:24]
        return os.path.join(self.store_dir, name + SPILL_SUFFIX)

    def _manifest_path(self, key: Tuple[str, str]) -> str:
        from raft_tpu.training.state import manifest_path

        return manifest_path(self.path(key))

    def _incident(self, detail: str) -> None:
        self.stats["corrupt"] += 1
        logger.warning("spill store: %s", detail)
        if self._on_incident is not None:
            self._on_incident("fleet-cold-start", detail)

    @staticmethod
    def _atomic_write(path: str, data: bytes) -> None:
        """Atomic rename WITHOUT fsync: spill writes run on the
        serving hot path (every served stream frame), and warm state
        is ADVISORY — get() verifies size+sha before trusting, so a
        power-loss-torn entry degrades to a typed cold start, never
        corruption.  Paying an fsync per frame would tax the p95 the
        SLO gate measures for durability the design doesn't need
        (checkpoints, which DO need it, use training/state.py's
        fsync'd writer).  Unique tmp names: replicas' batcher threads
        may spill the same (workload, stream) key concurrently."""
        import tempfile

        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                   suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                logger.warning("spill store: orphan tmp %s", tmp)
            raise

    def put(self, key: Tuple[str, str], state: np.ndarray) -> None:
        """Write-through from a replica's ``_remember_stream``: atomic
        blob, then manifest (a kill between the renames leaves an
        unverifiable blob that ``get`` refuses — never a torn adopt)."""
        buf = io.BytesIO()
        np.save(buf, np.asarray(state), allow_pickle=False)
        data = buf.getvalue()
        manifest = {
            "v": SPILL_MANIFEST_VERSION,
            "workload": key[0], "stream": key[1],
            "size": len(data),
            "sha256": hashlib.sha256(data).hexdigest(),
            "shape": list(np.shape(state)),
            "dtype": str(np.asarray(state).dtype),
        }
        self._atomic_write(self.path(key), data)
        self._atomic_write(
            self._manifest_path(key),
            json.dumps(manifest, sort_keys=True).encode("utf-8"))
        self.stats["puts"] += 1

    def _read_verified(self, key: Tuple[str, str]) -> np.ndarray:
        """One manifest+blob read with full verification; raises on any
        mismatch or decode failure."""
        with open(self._manifest_path(key), encoding="utf-8") as f:
            manifest = json.load(f)
        with open(self.path(key), "rb") as f:
            data = f.read()
        if manifest.get("size") != len(data):
            raise ValueError(
                f"size mismatch: manifest {manifest.get('size')} vs "
                f"{len(data)} bytes — torn write")
        if hashlib.sha256(data).hexdigest() != manifest.get("sha256"):
            raise ValueError("sha256 mismatch — corrupted at rest")
        return np.load(io.BytesIO(data), allow_pickle=False)

    def get(self, key: Tuple[str, str]) -> Optional[np.ndarray]:
        """The verified state for ``key``, or None (missing: silent
        miss; unverifiable: typed ``fleet-cold-start`` + quarantine)."""
        path = self.path(key)
        if not os.path.exists(path):
            self.stats["misses"] += 1
            return None
        label = f"{key[0]}/{key[1]}"
        try:
            try:
                arr = self._read_verified(key)
            except Exception as first:  # noqa: BLE001 — one retry:
                # put() writes blob-then-manifest as two atomic renames,
                # so a reader landing between them pairs the NEW blob
                # with the OLD manifest; that transient must not
                # quarantine a valid fresh entry (the dying replica's
                # last spill is exactly what a kill-replica adoption is
                # reading for).  The short backoff gives a preempted
                # writer time to land its second rename — a bounded
                # grace, not a guarantee: a writer stalled longer
                # presents as torn and quarantines, which costs one
                # typed cold start (the store's documented degradation),
                # not correctness.
                logger.debug("spill store: %s verify failed once (%s); "
                             "re-reading after grace", label, first)
                time.sleep(0.05)
                arr = self._read_verified(key)
        except Exception as e:  # noqa: BLE001 — any verify/decode
            # failure means the warm state cannot be trusted; the typed
            # re-cold-start (not an error) is the contract
            self._incident(
                f"stream {label} spill state failed verification "
                f"({type(e).__name__}: {e}); typed re-cold-start — the "
                f"stream restarts its warm chain, the request is served")
            for p in (path, self._manifest_path(key)):
                try:
                    if os.path.exists(p):
                        os.replace(p, p + ".corrupt")
                except OSError:
                    logger.warning("spill store: could not quarantine %s",
                                   p)
            return None
        self.stats["hits"] += 1
        return arr


@dataclasses.dataclass
class _Pending:
    """One fleet request's bookkeeping between submit and terminal."""

    fid: int
    image1: np.ndarray
    image2: np.ndarray
    deadline_abs: Optional[float]
    stream: Optional[str]
    workload: str
    t_submit: float
    future: Future
    replica: Optional[str] = None
    rfut: Optional[Future] = None
    moved_from: Optional[str] = None
    attempts: int = 0
    # front-door trace context (obs/trace.py Trace); the replica-side
    # trace shares its id — the cross-ledger join key through a rescue
    trace: Optional[object] = None
    # terminal-ownership flag, guarded by the fleet lock: exactly ONE
    # path (completion callback or typed rejection) may count and
    # resolve this request — close()'s leftover sweep racing a late
    # completion would otherwise count it BOTH served and rejected,
    # driving "unaccounted" negative and firing a false FATAL
    # fleet-conservation on a run with zero silent drops
    done: bool = False


@dataclasses.dataclass
class _Replica:
    """A live replica handle: the server, its heartbeat publisher, and
    its measured warmup cost."""

    rid: str
    server: object
    heartbeat: ReplicaHeartbeat
    startup_s: float = 0.0
    restarts: int = 0


class FleetServer:
    """N FlowServer replicas under one stream-affinity front door.

    ``replica_factory(rid, spill_store)`` builds one UN-warmed
    FlowServer (the fleet warms it and measures the startup — pass a
    shared :class:`~raft_tpu.serve.aot.AOTCache` into the factory's
    engines to make restarts warm).  ``warmup()`` starts every replica
    and its heartbeat; the largest initial warmup is remembered as the
    cold-start baseline the rolling-restart gate compares against.
    """

    def __init__(self, replica_factory, n_replicas: int = 3,
                 spill_dir: Optional[str] = None,
                 ledger=None,
                 slo_ms: Optional[float] = None,
                 heartbeat_interval: float = 0.2,
                 kv=None,
                 max_place_attempts: int = 3,
                 clock=time.monotonic,
                 tracer=None):
        if n_replicas < 1:
            raise ValueError(f"need >= 1 replica, got {n_replicas}")
        self._factory = replica_factory
        self.replica_ids: Tuple[str, ...] = tuple(
            f"r{i}" for i in range(int(n_replicas)))
        self.ledger = ledger
        self.slo_ms = slo_ms
        self._clock = clock
        self._kv = kv if kv is not None else LocalKVStore()
        self._hb_interval = float(heartbeat_interval)
        self._max_attempts = int(max_place_attempts)
        # front-door tracing (obs/trace.py): None = OFF.  The front
        # door mints the trace id; replicas join on it.
        self.tracer = tracer
        if tracer is not None and tracer.slo_ms is None:
            tracer.slo_ms = slo_ms
        self.spill_store = (SpillStore(spill_dir,
                                       on_incident=self._incident)
                            if spill_dir else None)
        self.membership = FleetMembership(
            fleet_channel(self._kv, 0, len(self.replica_ids)),
            self.replica_ids, interval=self._hb_interval, clock=clock)
        self.router = FleetRouter(self.membership)
        self.latency = LatencyTracker()
        self.counters: Dict[str, int] = {
            "submitted": 0, "served": 0, "rejected_queue_full": 0,
            "rejected_deadline": 0, "rejected_bad_request": 0,
            "rejected_shutdown": 0, "rerouted": 0, "stream_moves": 0,
        }
        self._replica_served: Dict[str, int] = {}
        self._incident_counts: Dict[str, int] = {}
        self._restarts: List[Dict] = []
        self._pending: Dict[int, _Pending] = {}
        self._next_fid = 0
        self._lock = threading.Lock()
        self._closed = False
        self.cold_startup_s: Optional[float] = None
        self._replicas: Dict[str, _Replica] = {}
        for rid in self.replica_ids:
            self._replicas[rid] = self._build_replica(rid)

    # -- telemetry (the FlowServer sampling discipline) ---------------------

    def _incident(self, kind: str, detail: str,
                  sample: bool = True) -> None:
        with self._lock:
            n = self._incident_counts.get(kind, 0) + 1
            self._incident_counts[kind] = n
        if self.tracer is not None:
            # flight recorder: the fleet-level incident force-retains
            # every request in flight at the front door right now
            self.tracer.on_incident(kind)
        if self.ledger is None:
            return
        if sample and n > 1 and (n % INCIDENT_SAMPLE) != 0:
            return
        if sample and n > 1:
            detail = f"[{n} total so far, 1-in-{INCIDENT_SAMPLE} " \
                     f"sampled] {detail}"
        try:
            self.ledger.incident(kind, step=0, detail=detail)
        except (ValueError, OSError):
            logger.warning("fleet: incident %s not ledgered; counters "
                           "carry it", kind)

    # -- replica lifecycle ---------------------------------------------------

    def _build_replica(self, rid: str) -> _Replica:
        server = self._factory(rid, self.spill_store)
        idx = self.replica_ids.index(rid)
        channel = fleet_channel(self._kv, idx, len(self.replica_ids))
        hb = ReplicaHeartbeat(
            channel, lambda s=server: bool(s.health()["ok"]),
            interval=self._hb_interval, clock=self._clock)
        return _Replica(rid=rid, server=server, heartbeat=hb)

    def warmup(self) -> float:
        """Warm every replica (compile or AOT-load its executables),
        start heartbeats, record the cold-start baseline.  Returns
        total wall seconds."""
        total = 0.0
        for rid in self.replica_ids:
            rep = self._replicas[rid]
            t0 = time.perf_counter()
            rep.server.warmup()
            rep.startup_s = time.perf_counter() - t0
            total += rep.startup_s
            rep.heartbeat.start()
        # the largest initial warmup is the one that paid the compiles
        # (with a shared AOT cache the rest warm-load from its stores)
        self.cold_startup_s = max(
            self._replicas[r].startup_s for r in self.replica_ids)
        return total

    def _depths(self) -> Dict[str, int]:
        out = {}
        for rid, rep in self._replicas.items():
            try:
                out[rid] = len(rep.server.queue)
            except Exception as e:  # noqa: BLE001 — a dying replica's
                # depth read may fail mid-teardown; report it as
                # unplaceable rather than failing the routing decision
                logger.warning("fleet: depth read for %s failed (%s); "
                               "treating as full", rid,
                               type(e).__name__)
                out[rid] = 1 << 30
        return out

    # -- the admission edge --------------------------------------------------

    def submit(self, image1: np.ndarray, image2: np.ndarray,
               deadline_ms: Optional[float] = None,
               stream: Optional[str] = None,
               workload: str = "flow") -> Future:
        """Admit one request fleet-wide; returns the FLEET's future
        (replica reroutes are invisible to the caller).  Raises the
        typed :class:`RequestError` subclasses on admission rejection,
        same contract as :meth:`FlowServer.submit`."""
        with self._lock:
            self.counters["submitted"] += 1
            if self._closed:
                self.counters["rejected_shutdown"] += 1
                err: Optional[RequestError] = \
                    BadRequestError("fleet is shutting down")
            else:
                err = None
                fid = self._next_fid
                self._next_fid += 1
        if err is not None:
            self._incident(err.kind, str(err))
            raise err
        pend = _Pending(
            fid=fid, image1=image1, image2=image2,
            deadline_abs=(self._clock() + deadline_ms / 1000.0
                          if deadline_ms is not None else None),
            stream=stream, workload=workload,
            t_submit=self._clock(), future=Future(),
            trace=(self.tracer.begin(rid=fid, stream=stream,
                                     workload=workload)
                   if self.tracer is not None else None))
        try:
            self._place(pend)
        except RequestError as e:
            self._finish_rejected(pend, e)
            raise
        return pend.future

    def _reject_counter(self, err: RequestError) -> str:
        return {"queue-full": "rejected_queue_full",
                "deadline-exceeded": "rejected_deadline"}.get(
                    err.kind, "rejected_bad_request")

    def _finish_rejected(self, pend: _Pending, err: RequestError) -> None:
        with self._lock:
            if pend.done:
                return       # a completion already owned the terminal
            pend.done = True
            self._pending.pop(pend.fid, None)
            self.counters[self._reject_counter(err)] += 1
        if self.tracer is not None and pend.trace is not None:
            # terminal before the incident write: a completed rejected
            # trace sits in the flight-recorder ring when it flushes
            self.tracer.finish(pend.trace, f"rejected:{err.kind}")
        self._incident(err.kind, f"request {pend.fid}: {err}")
        if not pend.future.done() \
                and pend.future.set_running_or_notify_cancel():
            pend.future.set_exception(err)

    def _place(self, pend: _Pending, exclude: Tuple[str, ...] = ()) -> None:
        """Route + submit to a replica; retries across replicas when
        the chosen one died under us.  Raises typed on rejection."""
        last_err: Optional[RequestError] = None
        for _ in range(self._max_attempts):
            pend.attempts += 1
            if pend.deadline_abs is not None:
                left_ms = 1000.0 * (pend.deadline_abs - self._clock())
                if left_ms <= 0:
                    raise DeadlineExceededError(
                        f"request {pend.fid} expired before placement")
            else:
                left_ms = None
            try:
                target, moved = self.router.route(
                    pend.stream, self._depths(), pend.workload,
                    trace=pend.trace)
            except NoReplicaError as e:
                # admission-control shed: the fleet cannot place work
                # anywhere right now — same contract as a full queue
                raise QueueFullError(
                    f"no live replica to place request {pend.fid} "
                    f"({e})") from e
            if target in exclude:
                live = [r for r in self.membership.live()
                        if r not in exclude]
                if not live:
                    raise QueueFullError(
                        f"no live replica outside {sorted(exclude)} for "
                        f"request {pend.fid}")
                depths = self._depths()
                target = min(live, key=lambda r: (depths.get(r, 0), r))
            if moved is not None:
                with self._lock:
                    self.counters["stream_moves"] += 1
                pend.moved_from = moved
                self._incident(
                    "fleet-reroute",
                    f"stream {pend.workload}/{pend.stream} re-routed "
                    f"{moved} -> {target} (consistent-hash ring over "
                    f"the live membership)")
            rep = self._replicas[target]
            # trace_id only when tracing: the replica-side trace joins
            # on the front door's id (kwarg omitted on the off path so
            # reduced test doubles keep their submit signature)
            tkw = ({"trace_id": pend.trace.tid}
                   if pend.trace is not None else {})
            try:
                rfut = rep.server.submit(
                    pend.image1, pend.image2, deadline_ms=left_ms,
                    stream=pend.stream, workload=pend.workload, **tkw)
            except RequestError as e:
                if self._replicas.get(target) is not rep:
                    # raced a rolling-restart swap: the handle read
                    # above is the CLOSED pre-restart server but the
                    # replica itself is live again — retry on it (the
                    # fresh handle), don't reject or exclude it
                    last_err = e
                    continue
                if self.membership.mark(target) != "up":
                    # raced a death/drain: try the survivors
                    last_err = e
                    exclude = exclude + (target,)
                    continue
                raise
            if pend.trace is not None:
                # the placement hop: initial place, a ring-driven
                # stream move, or a rescue off a dead replica —
                # pend.replica still names the PREVIOUS one here
                rescue = bool(exclude)
                pend.trace.hop(
                    target,
                    moved_from=(pend.replica if rescue else moved),
                    reason=("rescue" if rescue
                            else ("stream-move" if moved is not None
                                  else None)))
                pend.trace.stamp("reroute" if rescue else "place")
            pend.replica = target
            with self._lock:
                self._pending[pend.fid] = pend
                pend.rfut = rfut
            rfut.add_done_callback(
                lambda f, fid=pend.fid: self._on_replica_done(fid, f))
            return
        raise (last_err if last_err is not None else QueueFullError(
            f"request {pend.fid} could not be placed after "
            f"{self._max_attempts} attempt(s)"))

    def _on_replica_done(self, fid: int, rfut: Future) -> None:
        with self._lock:
            pend = self._pending.pop(fid, None)
        if pend is None:
            return                      # already rescued or finished
        exc = rfut.exception()
        if isinstance(exc, ReplicaLostError):
            # the typed rescue: the request was queued on a replica
            # that died — re-place it on a survivor.  Routing this
            # through the FUTURE (not a scan of the pending map) makes
            # rescue immune to the submit-vs-kill race: a callback
            # attached after the future already failed still fires.
            with self._lock:
                closed = self._closed
                if not closed:
                    self.counters["rerouted"] += 1
            if closed:
                # a rescue landing mid-shutdown rejects typed instead
                # of re-placing on replicas that are being closed
                self._finish_rejected(pend, exc)
                return
            if pend.trace is not None:
                # close the dead replica's wait before re-placement:
                # the reroute phase then measures ONLY the rescue
                pend.trace.stamp("replica-wait")
                pend.trace.event("rescue", replica=pend.replica)
            self._incident(
                "fleet-reroute",
                f"request {pend.fid} rescued from dead replica "
                f"{pend.replica}; re-placed on a survivor")
            try:
                self._place(pend, exclude=(pend.replica,))
            except RequestError as e:
                self._finish_rejected(pend, e)
            return
        if exc is None:
            res = dict(rfut.result())
            res["replica"] = pend.replica
            with self._lock:
                if pend.done:
                    return   # close()'s leftover sweep already
                             # rejected this request typed; counting it
                             # served TOO would double its terminal
                pend.done = True
                self.counters["served"] += 1
                self._replica_served[pend.replica] = \
                    self._replica_served.get(pend.replica, 0) + 1
                # under the lock: completions arrive from EVERY
                # replica's batcher thread, and the tracker's reservoir
                # bookkeeping is not itself thread-safe
                self.latency.add(self._clock() - pend.t_submit)
            if pend.moved_from is not None and not res.get("warm"):
                # the stream moved but no verified spill state was
                # there to adopt: the typed re-cold-start leg
                self._incident(
                    "fleet-cold-start",
                    f"stream {pend.workload}/{pend.stream} re-routed "
                    f"from {pend.moved_from} with no adoptable warm "
                    f"state; typed re-cold-start (request served)")
            if self.tracer is not None and pend.trace is not None:
                pend.trace.stamp("replica-wait")
                self.tracer.finish(pend.trace, "served")
            if pend.future.set_running_or_notify_cancel():
                pend.future.set_result(res)
            return
        err = (exc if isinstance(exc, RequestError)
               else BadRequestError(f"replica failure: "
                                    f"{type(exc).__name__}: {exc}"))
        self._finish_rejected(pend, err)

    # -- failure + restart choreography --------------------------------------

    def kill_replica(self, rid: str) -> int:
        """Crash one replica and rescue its queued work.  Returns the
        number of orphaned requests handed to re-placement.  Each
        orphan's replica future fails with :class:`ReplicaLostError`;
        the completion callback (:meth:`_on_replica_done`) re-places it
        on a survivor — going through the future means a request whose
        callback attachment RACES this kill is still rescued (callbacks
        on an already-failed future fire immediately).  Streams owned
        by the dead replica re-route on their next frame
        (consistent-hash ring over the survivors) and adopt their
        spilled warm state."""
        if rid not in self._replicas:
            raise KeyError(f"unknown replica {rid!r}")
        self.membership.mark_dead(rid)
        rep = self._replicas[rid]
        rep.heartbeat.stop()
        self._incident(
            "fleet-replica-lost",
            f"replica {rid} lost; membership pruned, its queued "
            f"requests re-placed on survivors, its streams re-route "
            f"via the ring", sample=False)
        orphans = rep.server.kill()
        for req in orphans:
            if req.future.set_running_or_notify_cancel():
                req.future.set_exception(ReplicaLostError(
                    f"replica {rid} died with request {req.rid} still "
                    f"queued; the fleet re-places it on a survivor"))
        return len(orphans)

    def _await_drained(self, rid: str, timeout: float) -> bool:
        deadline = self._clock() + timeout
        rep = self._replicas[rid]
        while self._clock() < deadline:
            with self._lock:
                pending_here = any(p.replica == rid
                                   for p in self._pending.values())
            if not pending_here and len(rep.server.queue) == 0:
                return True
            time.sleep(0.01)
        return False

    def rolling_restart(self, drain_timeout: float = 60.0) -> List[Dict]:
        """Zero-downtime rolling restart: one replica at a time —
        drain (router stops assigning to it; its streams re-route and
        adopt spilled state), close, rebuild through the factory, warm
        restore (measured against the cold baseline), rejoin.  The
        other replicas serve throughout; the chaos row gates the fleet
        p95 staying flat through the roll."""
        results: List[Dict] = []
        for rid in self.replica_ids:
            rep = self._replicas[rid]
            if self.membership.mark(rid) == "dead":
                # a replica killed BEFORE the roll has crash semantics:
                # nothing to drain, and its server must NOT be closed —
                # a post-mortem run_end would book its rescued orphans
                # as unaccounted and fire a false FATAL
                # serve-conservation on the replica ledger.  The roll
                # just rebuilds it (same as the undrained branch below).
                drained = False
                rep.heartbeat.stop()
            else:
                self._incident(
                    "fleet-drain",
                    f"replica {rid} draining for rolling restart; new "
                    f"work routes to {len(self.replica_ids) - 1} "
                    f"peer(s)", sample=False)
                self.membership.mark_draining(rid)
                drained = self._await_drained(rid, drain_timeout)
                rep.heartbeat.stop()
                if not drained:
                    # rescue anything still stuck (a wedged replica
                    # must not block the roll): crash-path semantics
                    self.kill_replica(rid)
                else:
                    rep.server.close()
            new = self._build_replica(rid)
            t0 = time.perf_counter()
            new.server.warmup()
            new.startup_s = time.perf_counter() - t0
            new.restarts = rep.restarts + 1
            self._replicas[rid] = new
            self.membership.mark_live(rid)
            new.heartbeat.start()
            cold = self.cold_startup_s or float("nan")
            row = {"replica": rid, "warm_restore_s": round(new.startup_s, 3),
                   "cold_startup_s": round(cold, 3),
                   "warm_frac": (round(new.startup_s / cold, 3)
                                 if cold == cold and cold > 0
                                 else None),
                   "drained": drained}
            self._incident(
                "fleet-restart",
                f"replica {rid} restarted: warm restore "
                f"{row['warm_restore_s']}s vs cold startup "
                f"{row['cold_startup_s']}s "
                f"({row['warm_frac']}x); drained={drained}",
                sample=False)
            with self._lock:
                self._restarts.append(row)
            results.append(row)
        return results

    # -- probes / summary / shutdown -----------------------------------------

    def health(self) -> Dict:
        live = self.membership.live()
        return {
            "ok": bool(live),
            "live_replicas": live,
            "replicas": {rid: self.membership.mark(rid)
                         for rid in self.replica_ids},
            "queue_depths": self._depths(),
            "counters": dict(self.counters),
        }

    def fleet_summary(self) -> Dict:
        """The front-door ledger's ``run_end`` serving section: fleet-
        level conservation + latency, per-replica attribution, restart
        and spill economics."""
        with self._lock:
            counters = dict(self.counters)
            in_flight = len(self._pending)
            replica_served = dict(self._replica_served)
            restarts = list(self._restarts)
        rejected = (counters["rejected_queue_full"]
                    + counters["rejected_deadline"]
                    + counters["rejected_bad_request"]
                    + counters["rejected_shutdown"])
        summary = {
            **counters,
            "rejected_total": rejected,
            "in_flight": in_flight,
            "unaccounted": (counters["submitted"] - counters["served"]
                            - rejected - in_flight),
            **self.latency.percentiles_ms(),
            "latency_samples_ms": self.latency.sample_ms(),
            "slo_p95_ms": self.slo_ms,
            "replicas": {
                rid: {"status": self.membership.mark(rid),
                      "served": replica_served.get(rid, 0),
                      "startup_s": round(self._replicas[rid].startup_s, 3),
                      "restarts": self._replicas[rid].restarts}
                for rid in self.replica_ids},
            "cold_startup_s": (round(self.cold_startup_s, 3)
                               if self.cold_startup_s is not None
                               else None),
        }
        if restarts:
            summary["restarts"] = restarts
        if self.spill_store is not None:
            summary["spill_store"] = dict(self.spill_store.stats)
        if self.tracer is not None:
            summary["trace"] = {
                **self.tracer.summary(),
                "exemplars": self.tracer.exemplars({
                    "p50": summary.get("latency_p50_ms"),
                    "p95": summary.get("latency_p95_ms"),
                    "max": summary.get("latency_max_ms")}),
            }
        return summary

    def close(self, timeout: float = 30.0) -> Dict:
        """Drain in-flight work, close every live replica, write the
        fleet summary (with the FATAL ``fleet-conservation`` incident
        if the books don't balance), return it."""
        with self._lock:
            self._closed = True
        deadline = self._clock() + timeout
        while self._clock() < deadline:
            with self._lock:
                if not self._pending:
                    break
            time.sleep(0.01)
        for rid in self.replica_ids:
            rep = self._replicas[rid]
            rep.heartbeat.stop()
            if self.membership.mark(rid) != "dead":
                try:
                    rep.server.close()
                except Exception:  # noqa: BLE001 — one replica's bad
                    # shutdown must not eat the fleet summary
                    logger.exception("fleet: replica %s close failed",
                                     rid)
        # anything STILL pending after the drain window is rejected
        # typed (no silent drops at fleet shutdown either)
        with self._lock:
            leftovers = list(self._pending.values())
        for pend in leftovers:
            self._finish_rejected(pend, BadRequestError(
                f"request {pend.fid} still in flight at fleet "
                f"shutdown; rejected typed (no silent drops)"))
        summary = self.fleet_summary()
        if summary["unaccounted"]:
            self._incident(
                "fleet-conservation",
                f"fleet request conservation violated at close: "
                f"{summary['unaccounted']} request(s) unaccounted for "
                f"(submitted != served + typed rejects) — a silent "
                f"drop crossed the fleet", sample=False)
        if self.tracer is not None:
            self.tracer.close()
        if self.ledger is not None:
            try:
                self.ledger.close(summary={"serving": summary})
            except (ValueError, OSError):
                logger.warning("fleet: final ledger close failed")
        return summary
