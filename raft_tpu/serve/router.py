"""Stream-affinity routing for the serving fleet.

RAFT video serving is *stateful*: each stream's ``flow_init`` warm
start lives on whichever replica served its last frame, so a fleet
front door cannot spray requests round-robin — a stream must keep
landing on the same replica while that replica is alive, and must move
to exactly ONE new replica (not a reshuffle) when it dies.  That is
the textbook consistent-hashing contract, and this module provides the
three host-side pieces the fleet composition (fleet.py) routes with:

- :class:`HashRing` — a deterministic consistent-hash ring (sha256
  points, virtual nodes).  ``assign(stream)`` is stable across calls
  and processes; removing a node moves only the streams that node
  owned (``~1/N`` of them), which is what keeps a replica death a
  bounded warm-state migration instead of a fleet-wide cold restart.
- :class:`LocalKVStore` — an in-process implementation of the
  jax.distributed coordination-service KV client surface
  (``key_value_set`` / ``key_value_delete`` / ``key_value_dir_get`` /
  ``blocking_key_value_get``), so :class:`~raft_tpu.parallel.elastic.
  PodChannel` — the PR 7 pod-agreement protocol — runs UNCHANGED as
  the fleet's membership/health transport.  A fleet of in-process
  replicas (the CPU test/bench/chaos shape) and a fleet of real hosts
  (the production shape, where the jax.distributed client backs the
  same four methods) share one membership code path.
- :class:`FleetMembership` — the live-replica view: every replica's
  heartbeat thread ``put``\\ s its health snapshot through its own
  PodChannel; the router reads ``poll("hb")`` and calls a replica live
  iff its heartbeat is fresh AND healthy AND it is not explicitly
  marked dead/draining (the kill/rolling-restart paths mark
  synchronously — detection must not wait out a heartbeat interval
  when the fleet itself did the killing).

Routing policy (:class:`FleetRouter`): a request WITH a stream id goes
to ``ring.assign(stream)`` over the live set; a stateless request goes
to the live replica with the shallowest queue (pure load balancing —
there is no state to keep together).  The router remembers each
stream's last target so a changed assignment is a *detected* event
(``fleet-reroute`` — the fleet ledgers it typed) rather than a silent
move.
"""

from __future__ import annotations

import bisect
import hashlib
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from raft_tpu.parallel.elastic import PodChannel

logger = logging.getLogger(__name__)

# A replica is dead when its last heartbeat is older than this many
# heartbeat intervals (the membership view's staleness bound).  3x
# tolerates one missed beat under scheduler jitter without calling a
# healthy replica dead.
HEARTBEAT_STALE_FACTOR = 3.0


def _point(key: str) -> int:
    """Deterministic 64-bit ring position (sha256 prefix — stable
    across processes and Python hash randomization)."""
    return int.from_bytes(
        hashlib.sha256(key.encode("utf-8")).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring over replica ids.

    ``vnodes`` virtual points per node smooth the ownership split
    (64 keeps the max/min stream share within ~2x at N=3).  The ring
    is immutable; membership changes build a new one (``without``, or
    the constructor with the grown node list) so a routing decision
    never sees a half-updated ring.
    """

    def __init__(self, nodes: Sequence[str], vnodes: int = 64):
        self.nodes: Tuple[str, ...] = tuple(sorted(set(nodes)))
        self.vnodes = int(vnodes)
        points: List[Tuple[int, str]] = []
        for node in self.nodes:
            for v in range(self.vnodes):
                points.append((_point(f"{node}#{v}"), node))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [n for _, n in points]

    def assign(self, key: str) -> str:
        """The owning node for ``key`` (first ring point clockwise)."""
        if not self.nodes:
            raise ValueError("hash ring has no nodes")
        i = bisect.bisect_right(self._points, _point(key))
        if i == len(self._points):
            i = 0
        return self._owners[i]

    def without(self, *nodes: str) -> "HashRing":
        return HashRing([n for n in self.nodes if n not in nodes],
                        vnodes=self.vnodes)


class LocalKVStore:
    """In-process stand-in for the jax.distributed coordination-service
    KV client — the four methods :class:`PodChannel` calls, with the
    same semantics (``set`` refuses overwrites with an ALREADY_EXISTS
    error, ``dir_get`` is a prefix scan, ``blocking_key_value_get``
    waits).  Lets the fleet reuse the PR 7 agreement protocol verbatim
    when the replicas are threads of one process instead of hosts."""

    def __init__(self):
        self._store: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._changed = threading.Condition(self._lock)

    def key_value_set(self, key: str, value: str) -> None:
        with self._changed:
            if key in self._store:
                raise RuntimeError(f"ALREADY_EXISTS: {key}")
            self._store[key] = str(value)
            self._changed.notify_all()

    def key_value_delete(self, key: str) -> None:
        with self._changed:
            self._store.pop(key, None)

    def key_value_dir_get(self, prefix: str) -> List[Tuple[str, str]]:
        with self._lock:
            return [(k, v) for k, v in sorted(self._store.items())
                    if k.startswith(prefix)]

    def blocking_key_value_get(self, key: str, timeout_ms: int) -> str:
        deadline = time.monotonic() + timeout_ms / 1000.0
        with self._changed:
            while key not in self._store:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(f"key {key} not posted within "
                                       f"{timeout_ms}ms")
                self._changed.wait(left)
            return self._store[key]


def fleet_channel(kv, replica_index: int, replica_count: int,
                  namespace: str = "fleet") -> PodChannel:
    """The PR 7 :class:`PodChannel` speaking for one fleet replica —
    same protocol, the fleet namespace, any KV client (the in-process
    :class:`LocalKVStore` or the real jax.distributed client)."""
    return PodChannel(kv, replica_index, replica_count,
                      namespace=namespace)


class FleetMembership:
    """The live-replica view the router reads.

    Sources, in precedence order:

    1. explicit marks (``mark_dead`` / ``mark_draining`` /
       ``mark_live``) — the kill and rolling-restart choreography is
       fleet-initiated, so detection is synchronous;
    2. the heartbeat channel: each replica publishes
       ``"<ok>:<monotonic>"`` through its PodChannel every
       ``interval`` seconds; a stale or not-ok heartbeat makes the
       replica not live (the crash-detection path for deaths the
       fleet did NOT cause).
    """

    def __init__(self, channel: PodChannel, replica_ids: Sequence[str],
                 interval: float = 0.2, clock=time.monotonic):
        self.channel = channel
        self.replica_ids: Tuple[str, ...] = tuple(replica_ids)
        self.interval = float(interval)
        self._clock = clock
        self._lock = threading.Lock()
        # replica id -> "up" | "draining" | "dead"
        self._marks: Dict[str, str] = {r: "up" for r in replica_ids}

    def _index(self, rid: str) -> int:
        return self.replica_ids.index(rid)

    def mark_dead(self, rid: str) -> None:
        with self._lock:
            self._marks[rid] = "dead"

    def mark_draining(self, rid: str) -> None:
        with self._lock:
            self._marks[rid] = "draining"

    def mark_live(self, rid: str) -> None:
        with self._lock:
            self._marks[rid] = "up"

    def mark(self, rid: str) -> str:
        with self._lock:
            return self._marks.get(rid, "dead")

    def heartbeats(self) -> Dict[int, Tuple[bool, float]]:
        """{replica index: (ok, age_seconds)} from the channel."""
        out: Dict[int, Tuple[bool, float]] = {}
        now = self._clock()
        for pid, value in self.channel.poll("hb").items():
            try:
                ok_s, t_s = str(value).split(":", 1)
                out[pid] = (ok_s == "1", now - float(t_s))
            except ValueError:
                out[pid] = (False, float("inf"))
        return out

    def live(self) -> List[str]:
        """Replica ids that may receive NEW work right now: marked up,
        with a fresh healthy heartbeat (or no heartbeat expected yet —
        a replica that never beat is trusted until its first interval
        elapses, so startup is not a routing dead zone)."""
        hbs = self.heartbeats()
        stale = HEARTBEAT_STALE_FACTOR * self.interval
        out = []
        for rid in self.replica_ids:
            if self.mark(rid) != "up":
                continue
            hb = hbs.get(self._index(rid))
            if hb is not None and (not hb[0] or hb[1] > stale):
                continue
            out.append(rid)
        return out


class ReplicaHeartbeat:
    """Per-replica publisher thread: ``health_fn() -> bool`` becomes
    ``"<ok>:<monotonic>"`` on the channel every ``interval`` seconds.
    ``stop()`` both joins the thread and leaves the LAST beat in place
    — a dead replica is detected by staleness, exactly like a host
    that stopped beating."""

    def __init__(self, channel: PodChannel, health_fn: Callable[[], bool],
                 interval: float = 0.2, clock=time.monotonic):
        self.channel = channel
        self._health = health_fn
        self.interval = float(interval)
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat_once(self) -> None:
        ok = "1" if self._health() else "0"
        self.channel.put("hb", f"{ok}:{self._clock():.4f}")

    def start(self) -> None:
        self.beat_once()               # membership sees us immediately
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"fleet-hb-{self.channel.process_index}")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.beat_once()
            except Exception as e:  # noqa: BLE001 — a heartbeat RPC
                # failure must not kill the publisher thread; a replica
                # that cannot beat goes STALE, which is the signal the
                # membership view already acts on
                logger.warning("fleet heartbeat %d: beat failed (%s: "
                               "%s); membership will see staleness",
                               self.channel.process_index,
                               type(e).__name__, e)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval * 4)
            self._thread = None


class FleetRouter:
    """Stream-affinity routing decisions over the membership view.

    ``route(stream, depths)``: streams ride the consistent-hash ring
    over the LIVE replicas; stateless requests go to the shallowest
    live queue.  The per-stream last-target memory (LRU-bounded, same
    rationale as the server's warm-state LRU) turns an assignment
    change into a reported reroute: ``route`` returns
    ``(replica_id, moved_from)`` with ``moved_from`` non-None exactly
    when a previously-routed stream changed owner."""

    def __init__(self, membership: FleetMembership,
                 vnodes: int = 64, max_streams: int = 4096):
        import collections

        self.membership = membership
        self._vnodes = int(vnodes)
        self._rings: Dict[Tuple[str, ...], HashRing] = {}
        self._last: "collections.OrderedDict[str, str]" = \
            collections.OrderedDict()
        self._max_streams = int(max_streams)
        self._lock = threading.Lock()

    def _ring(self, live: List[str]) -> HashRing:
        key = tuple(sorted(live))
        ring = self._rings.get(key)
        if ring is None:
            ring = HashRing(key, vnodes=self._vnodes)
            self._rings[key] = ring
        return ring

    def route(self, stream: Optional[str],
              depths: Dict[str, int],
              workload: str = "flow",
              trace=None) -> Tuple[str, Optional[str]]:
        """(target replica id, moved_from).  Raises
        :class:`NoReplicaError` when no replica is live.  ``trace``
        (an obs/trace.py Trace, optional) records the routing decision
        — which policy picked the target and over how many live
        replicas — as a point annotation on the request's timeline."""
        live = self.membership.live()
        if not live:
            raise NoReplicaError("no live replica in the fleet")
        if stream is None:
            target = min(live, key=lambda r: (depths.get(r, 0), r))
            if trace is not None:
                trace.event("route", policy="least-depth",
                            target=target, live=len(live))
            return target, None
        target = self._ring(live).assign(f"{workload}/{stream}")
        with self._lock:
            key = f"{workload}/{stream}"
            prev = self._last.get(key)
            self._last[key] = target
            self._last.move_to_end(key)
            while len(self._last) > self._max_streams:
                self._last.popitem(last=False)
        moved_from = prev if prev is not None and prev != target else None
        if trace is not None:
            trace.event("route", policy="ring", target=target,
                        live=len(live))
        return target, moved_from


class NoReplicaError(RuntimeError):
    """Every replica is dead or draining — the fleet cannot place the
    request anywhere; the front door converts this into a typed
    rejection (never a hang or a silent drop)."""
