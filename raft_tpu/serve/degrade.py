"""Graceful degradation: the adaptive refinement-iteration controller.

RAFT's accuracy-vs-iterations curve is FLAT past ~8-12 refinement
iterations once training converges (the round-5 depth-stability runs:
12/24/32-iter EPE within noise of each other on the synthetic stage;
the paper's own video mode runs warm frames at reduced iterations).
That flatness is serving headroom: under queue pressure the server can
shed LATENCY instead of shedding REQUESTS, by stepping the iteration
count down a fixed ladder (32 -> 24 -> 16 -> 8 by default) and back up
when pressure clears.  Warm-started video frames (``flow_init``
chaining) sit even further inside the flat region — the controller
exposes a separate, lower floor for fully-warm batches.

Every level transition is a typed ledger incident (``serve-degraded``
on the way down, ``serve-restored`` on return to full quality), so the
active degradation level is an incident SPAN in the run ledger: the
report shows exactly when quality was traded and for how long, and a
chaos run can gate on "the controller engaged and the run recovered".

The controller is deliberately host-side and deterministic: one
decision per dispatched batch, hysteresis via distinct high/low
watermarks plus a cooldown (in decisions) between steps, so a noisy
queue cannot make it thrash.  Signals: queue pressure (depth fraction)
and, when an SLO is configured, the rolling p95 latency.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence


DEFAULT_ITER_LEVELS = (32, 24, 16, 8)


class IterationController:
    """Steps refinement iterations down under pressure, up when clear.

    ``levels`` is the iteration ladder, full quality first, strictly
    decreasing.  ``observe`` is called once per dispatched batch with
    the current queue fraction (and rolling p95 latency when known) and
    returns the iteration count the NEXT batch should run.
    """

    def __init__(self, levels: Sequence[int] = DEFAULT_ITER_LEVELS,
                 queue_high: float = 0.75, queue_low: float = 0.25,
                 slo_ms: Optional[float] = None,
                 cooldown: int = 2,
                 record: Optional[Callable[[str, str], None]] = None,
                 clock=time.monotonic):
        levels = tuple(int(x) for x in levels)
        if not levels or any(b >= a for a, b in zip(levels, levels[1:])):
            raise ValueError(f"levels must be non-empty and strictly "
                             f"decreasing, got {levels}")
        if not 0.0 <= queue_low < queue_high <= 1.0:
            raise ValueError(f"need 0 <= queue_low < queue_high <= 1, "
                             f"got {queue_low}/{queue_high}")
        self.levels = levels
        self.queue_high = queue_high
        self.queue_low = queue_low
        self.slo_ms = slo_ms
        self.cooldown = int(cooldown)
        self._record = record
        self._clock = clock
        self.level = 0
        self.max_level_seen = 0
        self.transitions: List[Dict] = []
        self._since_change = self.cooldown  # free to act immediately

    @property
    def iters(self) -> int:
        return self.levels[self.level]

    def _change(self, new_level: int, why: str) -> None:
        old = self.level
        self.level = new_level
        self.max_level_seen = max(self.max_level_seen, new_level)
        self._since_change = 0
        self.transitions.append({
            "t": self._clock(), "from": old, "to": new_level,
            "iters": self.levels[new_level], "why": why,
        })
        if self._record is None:
            return
        if new_level > old:
            self._record(
                "serve-degraded",
                f"degradation level {old} -> {new_level}: refinement "
                f"iterations {self.levels[old]} -> "
                f"{self.levels[new_level]} ({why}); accuracy held by the "
                f"flat iteration curve, latency shed instead of requests")
        else:
            self._record(
                "serve-restored",
                f"degradation level {old} -> {new_level}: refinement "
                f"iterations restored to {self.levels[new_level]} ({why})")

    def observe(self, queue_frac: float,
                p95_ms: Optional[float] = None) -> int:
        """One decision; returns the iteration count for the next batch."""
        self._since_change += 1
        if self._since_change <= self.cooldown:
            return self.iters
        over_slo = (self.slo_ms is not None and p95_ms is not None
                    and p95_ms > self.slo_ms)
        under_slo = (self.slo_ms is None or p95_ms is None
                     or p95_ms <= 0.8 * self.slo_ms)
        if (queue_frac >= self.queue_high or over_slo) \
                and self.level + 1 < len(self.levels):
            why = (f"queue at {queue_frac:.0%}" if queue_frac
                   >= self.queue_high
                   else f"p95 {p95_ms:.0f}ms > SLO {self.slo_ms:.0f}ms")
            self._change(self.level + 1, why)
        elif queue_frac <= self.queue_low and under_slo and self.level > 0:
            self._change(self.level - 1,
                         f"queue drained to {queue_frac:.0%}")
        return self.iters

    def summary(self) -> Dict:
        """Counters for the ledger's run_end serving summary."""
        return {
            "levels": list(self.levels),
            "final_level": self.level,
            "max_level": self.max_level_seen,
            "transitions": len(self.transitions),
        }


class LatencyTracker:
    """Bounded reservoir of per-request latencies with rolling
    percentiles — the controller's p95 signal and the report's SLO
    numbers, without holding a million floats at millions-of-users
    scale.

    The summary reservoir is true reservoir sampling (Vitter's R:
    past the cap, sample i replaces a uniformly-random slot with
    probability cap/i) so the run-end percentiles weight the WHOLE
    run — a fill-once buffer would report only the earliest traffic
    and let a late SLO collapse gate green."""

    def __init__(self, window: int = 512, reservoir: int = 65536,
                 seed: int = 0):
        import collections

        import numpy as np

        self.window = collections.deque(maxlen=window)
        self._reservoir_cap = reservoir
        self._rng = np.random.default_rng(seed)
        self.samples: List[float] = []
        self.count = 0

    def add(self, latency_s: float) -> None:
        self.count += 1
        self.window.append(latency_s)
        if len(self.samples) < self._reservoir_cap:
            self.samples.append(latency_s)
        else:
            j = int(self._rng.integers(0, self.count))
            if j < self._reservoir_cap:
                self.samples[j] = latency_s

    def rolling_p95_ms(self) -> Optional[float]:
        if not self.window:
            return None
        import numpy as np

        return 1000.0 * float(np.percentile(list(self.window), 95))

    def sample_ms(self, cap: int = 256) -> List[float]:
        """Bounded quantile sketch for cross-replica pooling: up to
        ``cap`` evenly-spaced order statistics of the reservoir (all
        samples below the cap, so small runs pool exactly).  The fleet
        report computes its fleet-wide percentiles from the pooled
        sketches — per-replica percentiles cannot be merged."""
        import numpy as np

        if not self.samples:
            return []
        # graftlint: disable=f64-literal -- host-side latency seconds;
        # never reaches a device
        arr = np.sort(np.asarray(self.samples, dtype=np.float64))
        if arr.size > cap:
            idx = np.linspace(0, arr.size - 1, cap).round().astype(int)
            arr = arr[idx]
        return [round(1000.0 * float(x), 3) for x in arr]

    def percentiles_ms(self) -> Dict[str, float]:
        import numpy as np

        if not self.samples:
            nan = float("nan")
            return {"latency_p50_ms": nan, "latency_p95_ms": nan,
                    "latency_max_ms": nan}
        arr = np.asarray(self.samples)
        return {
            "latency_p50_ms": round(1000.0 * float(np.percentile(arr, 50)), 3),
            "latency_p95_ms": round(1000.0 * float(np.percentile(arr, 95)), 3),
            "latency_max_ms": round(1000.0 * float(arr.max()), 3),
        }
