"""The serving executor: bucketed, AOT-compiled bf16 inference graphs.

Request images arrive at arbitrary sizes; XLA executables want static
shapes.  The resolution is the same one the device-aug wire uses
(data/device_aug.py pads raw frames to per-family static shapes): a
fixed table of **bucket families** derived from ``DEVICE_AUG_PAD``
(rounded up to /8 for the encoder stride), each compiled ONCE per
(batch capacity, iteration count, warm/cold) at a static shape.  A
request maps to the smallest family that holds it, is edge-padded to
the family shape (replicate padding — the ``InputPadder`` convention,
anchored top-left so unpadding is a crop), and rides a fixed-capacity
batch whose empty slots are zero-filled.  Empty-slot outputs are
discarded; a zero slot is also exactly what a rejected (poisoned)
request's slot becomes, which is what makes per-slot isolation
bit-exact (see batcher.py).

Executables are built through :class:`~raft_tpu.serve.aot.AOTCache`
when one is attached: ``jax.jit(...).lower(...).compile()`` at startup,
serialized to disk, verified-on-load at the next startup — the
warm-restart path.  The model runs the bf16 inference policy
(``compute_dtype=corr_dtype=bfloat16``) by default: serving has no
optimizer to protect and flow leaves the graph f32 either way (the
declared boundary the graftlint engines pin).

``abstract_serve_forward`` is the lowerable entry point behind the
``serve_forward``/``serve_forward_warm`` records in
``raft_tpu/entrypoints.py`` — exactly the graph ``ServeEngine``
compiles, built without weights or an engine instance, audited by all
five static-analysis engines.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional, Tuple

import numpy as np

# The AOT cache-key recipe lives ON THE REGISTRY
# (raft_tpu/entrypoints.py) — one definition, imported by both cache
# consumers (these serving executors and the Evaluator's AOT path), so
# the two can never drift again.  Re-exported here because this module
# remains the conventional import site.
from raft_tpu.entrypoints import (arg_signature, forward_cache_key,  # noqa: F401
                                  tree_signature as _tree_signature)

logger = logging.getLogger(__name__)

# Bucket families: name -> static (H, W), /8-divisible (the encoder
# downsamples by 8; InputPadder's rule).  Derived from the device-aug
# wire's per-family raw pads (datasets.DEVICE_AUG_PAD), rounded UP to
# /8 so every release frame of the family fits; "tiny" serves the
# CPU-smoke/test sizes.  Order does not matter — requests map to the
# smallest-area family that holds them.
def _round8(x: int) -> int:
    return ((x + 7) // 8) * 8


def default_buckets() -> Dict[str, Tuple[int, int]]:
    from raft_tpu.data.datasets import DEVICE_AUG_PAD

    buckets = {"tiny": (64, 64)}
    for family, (h, w) in DEVICE_AUG_PAD.items():
        buckets[family.lower()] = (_round8(h), _round8(w))
    return buckets


def bucket_for(h: int, w: int,
               buckets: Dict[str, Tuple[int, int]]) -> Optional[str]:
    """The smallest-area family holding an (h, w) image, or None."""
    best, best_area = None, None
    for name, (bh, bw) in buckets.items():
        if h <= bh and w <= bw:
            area = bh * bw
            if best_area is None or area < best_area:
                best, best_area = name, area
    return best


def pad_to_bucket(img: np.ndarray, hw: Tuple[int, int]) -> np.ndarray:
    """Edge-pad an (H, W, C) image to the family shape, anchored
    top-left (unpad = crop ``[:h, :w]``)."""
    H, W = hw
    h, w = img.shape[:2]
    if (h, w) == (H, W):
        return img
    return np.pad(img, ((0, H - h), (0, W - w), (0, 0)), mode="edge")


def serve_config(small: bool = False, overrides: Optional[Dict] = None):
    """The serving model config: bf16 inference policy over the
    standard architecture (overridable for tests/benches)."""
    from raft_tpu.config import RAFTConfig

    kw = {"small": small, "compute_dtype": "bfloat16",
          "corr_dtype": "bfloat16"}
    kw.update(overrides or {})
    return RAFTConfig(**kw)


def abstract_serve_forward(iters: int = 2, hw: Tuple[int, int] = (64, 64),
                           batch: int = 2, warm: bool = False,
                           overrides: Optional[Dict] = None):
    """The serving executor's jitted batched bf16 test_mode forward over
    abstract inputs: the lowerable entry point the static-analysis
    engines audit (exactly the graph :meth:`ServeEngine.executable`
    compiles, built without weights).

    ``warm=True`` is the video variant with the ``flow_init`` warm-start
    argument (B, H/8, W/8, 2).  Returns ``(fwd, args_sds)`` with ``fwd``
    supporting ``.lower(*args_sds)``.
    """
    import jax
    import jax.numpy as jnp

    from raft_tpu.models import RAFT

    model = RAFT(serve_config(overrides=dict(overrides or {})))
    H, W = hw
    img_sds = jax.ShapeDtypeStruct((batch, H, W, 3), jnp.float32)
    variables_sds = jax.eval_shape(
        lambda rng, a, b: model.init(rng, a, b, iters=iters, train=True),
        jax.random.PRNGKey(0), img_sds, img_sds)
    fwd = make_test_forward(model, iters, warm=warm)
    if warm:
        flow_sds = jax.ShapeDtypeStruct((batch, H // 8, W // 8, 2),
                                        jnp.float32)
        return fwd, (variables_sds, img_sds, img_sds, flow_sds)
    return fwd, (variables_sds, img_sds, img_sds)


def make_test_forward(model, iters: int, warm: bool):
    """THE jitted test_mode forward (cold, or the ``flow_init``
    warm-start variant) — single definition shared by the serving
    executors, the Evaluator (both its jit and AOT paths), and
    :func:`abstract_serve_forward`, so the graph the graftlint engines
    audit is the graph production compiles and serves."""
    import jax

    if warm:
        # flow_init is consumed at graph entry and replaced by the
        # returned flow of the same shape/dtype — donate it so XLA
        # aliases the buffers (every caller passes a fresh host array
        # or the previous output it is about to overwrite)
        return jax.jit(lambda v, a, b, f: model.apply(
            v, a, b, iters=iters, flow_init=f, test_mode=True),
            donate_argnums=(3,))
    return jax.jit(lambda v, a, b: model.apply(
        v, a, b, iters=iters, test_mode=True))


def compile_test_forward(model, variables, img1_sds, img2_sds,
                         iters: int, flow_sds=None):
    """lower -> compile :func:`make_test_forward` — THE build recipe
    behind every AOT-cached executable.  ``flow_sds`` selects the
    ``flow_init`` warm-start variant."""
    fn = make_test_forward(model, iters, warm=flow_sds is not None)
    if flow_sds is not None:
        return fn.lower(variables, img1_sds, img2_sds,
                        flow_sds).compile()
    return fn.lower(variables, img1_sds, img2_sds).compile()


class ServeEngine:
    """Compiles and runs the bucketed serving forwards.

    One executable per (family shape, iteration count, warm) — the
    degradation controller's iteration levels each get their own, all
    warmed at startup so a load-shed decision never pays a compile.
    With an :class:`AOTCache` attached, startup loads verified
    executables from disk (warm restart) and stores fresh compiles.
    """

    def __init__(self, model, variables, batch_size: int = 4,
                 aot_cache=None, spans=None,
                 compile_fn=None, cache_tag: str = "serve_forward",
                 warm_channels: int = 2):
        import threading

        from raft_tpu.obs.spans import NULL

        self.model = model
        self.variables = variables
        self.batch_size = int(batch_size)
        self.aot = aot_cache
        self.spans = spans if spans is not None else NULL
        # Workload hooks: ``compile_fn`` is the lower->compile recipe
        # (default: the flow forward; the stereo workload passes
        # workloads.stereo.compile_stereo_forward), ``cache_tag``
        # namespaces the AOT cache key per workload (two workloads'
        # executables must never collide on a key), ``warm_channels``
        # is the per-pixel width of the warm-start init (2 = flow_init,
        # 1 = disp_init).
        self.compile_fn = compile_fn or compile_test_forward
        self.cache_tag = cache_tag
        self.warm_channels = int(warm_channels)
        self._fns: Dict[tuple, object] = {}
        # the caller-thread warmup and the batcher thread can race the
        # same memo miss; serializing the compile path avoids paying
        # one multi-second XLA compile twice (and two racing cache
        # stores for one key)
        self._compile_lock = threading.Lock()
        self._var_sig = None

    def _cache_key(self, hw: Tuple[int, int], iters: int,
                   warm: bool) -> str:
        if self._var_sig is None:
            self._var_sig = _tree_signature(self.variables)
        H, W = hw
        img = ((self.batch_size, H, W, 3), "float32")
        sig = (img, img) + ((((self.batch_size, H // 8, W // 8,
                               self.warm_channels),
                              "float32"),) if warm else ())
        return forward_cache_key(self.cache_tag, self.model,
                                 self._var_sig, sig, iters, warm)

    def _build(self, hw: Tuple[int, int], iters: int, warm: bool):
        import jax
        import jax.numpy as jnp

        H, W = hw
        B = self.batch_size
        img_sds = jax.ShapeDtypeStruct((B, H, W, 3), jnp.float32)
        flow_sds = (jax.ShapeDtypeStruct((B, H // 8, W // 8,
                                          self.warm_channels),
                                         jnp.float32) if warm else None)
        return self.compile_fn(self.model, self.variables, img_sds,
                               img_sds, iters, flow_sds=flow_sds)

    def invalidate(self, hw: Tuple[int, int], iters: int,
                   warm: bool = False) -> bool:
        """Drop the in-process memo for one executable so the next call
        re-verifies-and-loads from the AOT cache (or recompiles) — the
        serve canary's recompile-and-recheck hook (server.py): a
        golden-digest mismatch evicts the suspect executable and the
        recheck decides whether the corruption lived in it (healed) or
        in the chip (fatal).  Returns whether an entry was dropped."""
        with self._compile_lock:
            key = (tuple(hw), int(iters), bool(warm))
            return self._fns.pop(key, None) is not None

    def is_compiled(self, hw: Tuple[int, int], iters: int,
                    warm: bool = False) -> bool:
        """Is this executable already in the in-process memo? (The
        server widens its watchdog bracket when a dispatch will pay a
        lazy compile/cache-load first.)"""
        return (tuple(hw), int(iters), bool(warm)) in self._fns

    def executable(self, hw: Tuple[int, int], iters: int,
                   warm: bool = False):
        """The compiled forward for (family shape, iters, warm) —
        memoized in-process, AOT-cached on disk when configured."""
        mkey = (tuple(hw), int(iters), bool(warm))
        fn = self._fns.get(mkey)
        if fn is not None:
            return fn
        with self._compile_lock:
            fn = self._fns.get(mkey)     # a racing thread compiled it
            if fn is not None:
                return fn
            label = (f"{self.cache_tag} B={self.batch_size} hw={hw} "
                     f"iters={iters} warm={warm}")
            if self.aot is not None:
                fn, was_warm = self.aot.get_or_compile(
                    self._cache_key(hw, iters, warm),
                    lambda: self._build(hw, iters, warm), label=label)
                logger.info("serve: %s (%s)", label,
                            "warm cache load" if was_warm
                            else "cold compile")
            else:
                t0 = time.perf_counter()
                fn = self._build(hw, iters, warm)
                logger.info("serve: %s cold compile (%.2fs, no AOT "
                            "cache)", label, time.perf_counter() - t0)
            self._fns[mkey] = fn
            return fn

    def warmup(self, families: Dict[str, Tuple[int, int]],
               iters_levels, warm_too: bool = True) -> float:
        """Compile/load every (family, level[, warm]) executable; the
        startup cost (the number the warm-restart gate measures).
        Returns wall seconds."""
        t0 = time.perf_counter()
        for hw in families.values():
            for iters in iters_levels:
                self.executable(hw, iters, warm=False)
                if warm_too:
                    self.executable(hw, iters, warm=True)
        return time.perf_counter() - t0

    def forward(self, hw: Tuple[int, int], iters: int,
                img1: np.ndarray, img2: np.ndarray,
                flow_init: Optional[np.ndarray] = None):
        """Run one padded batch; returns host (flow_low, flow_up).

        The host conversion is the dispatch-completion barrier — the
        caller's dispatch span measures real execution, and the
        watchdog's progress notification happens after work provably
        finished.
        """
        warm = flow_init is not None
        fn = self.executable(hw, iters, warm=warm)
        with self.spans.span("dispatch"):
            if warm:
                flow_low, flow_up = fn(self.variables, img1, img2,
                                       flow_init)
            else:
                flow_low, flow_up = fn(self.variables, img1, img2)
            return np.asarray(flow_low), np.asarray(flow_up)
