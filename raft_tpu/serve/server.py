"""FlowServer: the fault-tolerant serving composition.

queue -> batcher -> AOT executor -> degradation controller, with the
dispatch watchdog underneath and the obs ledger throughout:

- :meth:`submit` is the admission edge (typed ``queue-full`` /
  ``bad-request`` rejections raise to the caller AND land in the
  ledger);
- one daemon batcher thread assembles deadline-checked, poison-masked,
  family-padded batches (batcher.py) and dispatches them through the
  AOT-compiled bucket executables (engine.py);
- the iteration controller (degrade.py) picks each batch's refinement
  depth from queue pressure and rolling p95 latency; video streams
  chain ``flow_init`` warm starts per stream id;
- the dispatch watchdog (watchdog.py) converts a wedged compile or
  dispatch into a typed ``serve-stalled`` incident and a nonzero exit;
- ``health()``/``ready()`` are the probe surfaces, and ``close()``
  writes the serving summary (request conservation counters, latency
  percentiles vs SLO, degradation history, AOT cache stats) into the
  ledger's ``run_end`` record — the numbers ``obs report``'s serving
  section and its ``--fail-on-slo`` gate consume.

Request conservation (NO silent drops) is a structural invariant:
``submitted == served + rejected + in-flight`` at every instant, and
the summary asserts the terminal form of it at close.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

import numpy as np

from raft_tpu.serve.batcher import (BadRequestError, DeadlineExceededError,
                                    RequestError, RequestQueue,
                                    assemble_batch)
from raft_tpu.serve.degrade import (DEFAULT_ITER_LEVELS, IterationController,
                                    LatencyTracker)
from raft_tpu.serve.watchdog import DispatchWatchdog

logger = logging.getLogger(__name__)

# Ledger bloat guard: a deadline storm is ONE event operationally, not
# ten thousand; per incident kind the first firing is always recorded
# and afterwards every INCIDENT_SAMPLE-th, with the counters carrying
# the exact totals (the conservation law never depends on the ledger).
INCIDENT_SAMPLE = 100


class FlowServer:
    """Admission-controlled, deadline-aware batched flow inference."""

    def __init__(self, engine, buckets: Optional[Dict] = None,
                 queue_capacity: int = 64,
                 iter_levels=DEFAULT_ITER_LEVELS,
                 slo_ms: Optional[float] = None,
                 degrade: bool = True,
                 warm_iters: Optional[int] = None,
                 ledger=None,
                 watchdog_timeout_s: Optional[float] = None,
                 flush_every: int = 8,
                 max_streams: int = 256,
                 clock=time.monotonic,
                 exit_fn=None,
                 spill_store=None,
                 continuous: bool = False,
                 segment_iters: Optional[int] = None,
                 canary_every: int = 0,
                 tracer=None):
        from raft_tpu.obs.spans import NULL, SpanRecorder
        from raft_tpu.serve.engine import default_buckets

        # ``engine`` may be one engine (classic single-workload server:
        # it serves as workload "flow") or a dict {workload: engine}
        # (heterogeneous serving: flow + stereo through ONE queue,
        # batcher and degradation controller — a batch never mixes
        # workloads, see batcher.py lanes).  All engines must agree on
        # batch_size: the batcher's pop quantum is one dispatch.
        self.engines: Dict[str, object] = (
            dict(engine) if isinstance(engine, dict)
            else {"flow": engine})
        if not self.engines:
            raise ValueError("FlowServer needs at least one engine")
        sizes = {e.batch_size for e in self.engines.values()}
        if len(sizes) > 1:
            raise ValueError(
                f"engines disagree on batch_size ({sorted(sizes)}); the "
                f"batcher's pop quantum is one dispatch")
        # the default engine: single-engine servers keep the historic
        # attribute; multi-engine servers use it for capacity numbers
        self.engine = next(iter(self.engines.values()))
        self.buckets = dict(buckets or default_buckets())
        self.queue = RequestQueue(queue_capacity, self.buckets)
        self.slo_ms = slo_ms
        self.warm_iters = warm_iters
        self.ledger = ledger
        self._clock = clock
        self._flush_every = int(flush_every)
        self.spans = (SpanRecorder(ledger=ledger, annotate=False)
                      if ledger is not None else NULL)
        # per-request tracing (obs/trace.py): None means OFF — the off
        # path allocates no trace structures per request at all.  The
        # tracer inherits this server's SLO so SLO-violating requests
        # are force-retained past head sampling.
        self.tracer = tracer
        if tracer is not None and tracer.slo_ms is None:
            tracer.slo_ms = slo_ms
        # canary interleave annotation: the most recent probe's cost,
        # attached as an event to the NEXT assembled batch's traces
        # (batcher-thread-only state)
        self._canary_ms_pending = 0.0
        for eng in self.engines.values():
            if getattr(eng, "spans", None) is NULL or \
                    getattr(eng, "spans", None) is None:
                eng.spans = self.spans

        self.controller = IterationController(
            levels=iter_levels if degrade else iter_levels[:1],
            slo_ms=slo_ms,
            record=lambda kind, detail: self._incident(kind, detail,
                                                       sample=False))
        self.latency = LatencyTracker()
        self.counters: Dict[str, int] = {
            "submitted": 0, "served": 0, "rejected_queue_full": 0,
            "rejected_deadline": 0, "rejected_bad_request": 0,
            "rejected_shutdown": 0, "batches": 0,
        }
        # per-(workload, family) attribution: served counts + latency,
        # so heterogeneous traffic stays separable in the obs report
        # (one undifferentiated pool can hide a slow family behind a
        # fast one).  Keys render as "workload/family".
        self._family_latency: Dict[str, LatencyTracker] = {}
        self._family_counts: Dict[str, Dict[str, int]] = {}
        self._incident_counts: Dict[str, int] = {}
        # stream -> last flow_low, LRU-bounded: stream ids are
        # client-chosen and unbounded in a long-lived server; an
        # evicted stream simply cold-starts its next frame
        import collections
        self._streams: "collections.OrderedDict[str, np.ndarray]" = \
            collections.OrderedDict()
        self._max_streams = int(max_streams)
        # fleet integration: the shared on-disk warm-state spill store
        # (serve/fleet.py SpillStore, duck-typed: get/put over
        # (workload, stream) keys).  _remember_stream writes THROUGH to
        # it, so another replica can adopt this stream's warm state
        # after a death or a drain; _warm_inits falls back to it when
        # the local LRU misses (the verified warm-state adoption path).
        self.spill_store = spill_store
        # continuous batching: dispatch SEGMENTS of `segment_iters` GRU
        # iterations through the warm executable (flow_low re-fed as
        # flow_init) and admit new requests into freed/empty slots at
        # every segment boundary, instead of holding a FIFO assembly
        # barrier until a whole batch completes its full ladder depth.
        self.continuous = bool(continuous)
        if segment_iters is not None and int(segment_iters) < 1:
            raise ValueError(f"segment_iters must be >= 1, "
                             f"got {segment_iters}")
        # default segment = the ladder's smallest level: the executable
        # the degradation path already proves exists and warms
        self._segment = int(segment_iters if segment_iters is not None
                            else self.controller.levels[-1])
        # Serving SDC canary (resilience/sdc.py layer 4): one cached
        # (golden input, digest) pair per (workload, family), probed
        # every `canary_every` batches BETWEEN dispatches — a flaky
        # chip computing finite-but-wrong flow is caught by a bit-exact
        # digest compare against the warmup-time baseline, typed
        # `sdc-serve-canary`, and answered with executor
        # recompile-and-recheck before more wrong flow ships.  0
        # disables probing.
        if canary_every < 0:
            raise ValueError(f"canary_every must be >= 0, "
                             f"got {canary_every}")
        self.canary_every = int(canary_every)
        self._canary: Dict = {}            # (workload, family) -> record
        self._canary_counts = {"probes": 0, "mismatches": 0,
                               "recompiles": 0}
        self._canary_last = 0
        self._canary_rr = 0
        self._canary_failed = False
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._warm = False
        self._batch_no = 0
        self.watchdog: Optional[DispatchWatchdog] = None
        if watchdog_timeout_s is not None:
            kw = {} if exit_fn is None else {"exit_fn": exit_fn}
            self.watchdog = DispatchWatchdog(
                watchdog_timeout_s,
                on_incident=lambda kind, detail: self._incident(
                    kind, detail, sample=False),
                on_trip=lambda kind: self._flush_ledger(), **kw)
            self.watchdog.start()
        self._thread = threading.Thread(
            target=(self._serve_loop_continuous if self.continuous
                    else self._serve_loop),
            daemon=True, name="serve-batcher")
        self._thread.start()

    # -- telemetry -----------------------------------------------------------

    def _incident(self, kind: str, detail: str, sample: bool = True,
                  severity: Optional[str] = None) -> None:
        n = self._incident_counts.get(kind, 0) + 1
        self._incident_counts[kind] = n
        if self.tracer is not None:
            # flight recorder: flush the recent-trace ring and
            # force-retain every request alive right now (each records
            # at its own terminal with this incident named)
            self.tracer.on_incident(kind)
        if self.ledger is None:
            return
        if sample and n > 1 and (n % INCIDENT_SAMPLE) != 0:
            return
        if sample and n > 1:
            detail = f"[{n} total so far, 1-in-{INCIDENT_SAMPLE} " \
                     f"sampled] {detail}"
        try:
            self.ledger.incident(kind, step=self._batch_no, detail=detail,
                                 severity=severity)
        except (ValueError, OSError):
            # closed ledger (a submit racing shutdown) or failed disk
            # (ENOSPC): the typed rejection/counters are the contract —
            # telemetry I/O must never replace them with its own error
            # or kill the batcher thread
            logger.warning("serve: incident %s not ledgered (closed "
                           "or unwritable ledger); counters carry it",
                           kind)

    def _flush_ledger(self) -> None:
        if self.ledger is not None:
            try:
                self.spans.flush(self._batch_no)
            except Exception:  # flushing from a trip path: best-effort
                logger.warning("serve: span flush on trip failed")

    # -- admission edge ------------------------------------------------------

    def warmup(self, families: Optional[Dict] = None,
               warm_too: bool = True) -> float:
        """Compile/load every bucket executable at every iteration
        level; the startup cost.  Bracketed by the watchdog — a wedged
        COMPILE is a serve-stall too."""
        fams = dict(families) if families else self.buckets
        token = None
        if self.watchdog is not None:
            # slow=True: this bracket must KEEP the startup-factor
            # bound even if an overlapping lazy dispatch completes
            # first (completion flips the watchdog to steady state)
            token = self.watchdog.begin(
                f"warmup compile of {len(fams)} family(ies) x "
                f"{len(self.controller.levels)} level(s) x "
                f"{len(self.engines)} workload(s)", slow=True)
        try:
            secs = 0.0
            for eng in self.engines.values():
                if self.continuous:
                    # continuous batching dispatches ONLY warm-variant
                    # segments (flow state re-fed each boundary), so
                    # startup compiles exactly one executable per
                    # family — none of the ladder's per-level variants
                    t0 = time.perf_counter()
                    for hw in fams.values():
                        eng.executable(hw, self._segment, warm=True)
                    secs += time.perf_counter() - t0
                else:
                    secs += eng.warmup(fams, self.controller.levels,
                                       warm_too=warm_too)
            if self.canary_every:
                # INSIDE the watchdog bracket: the baseline dispatches
                # real forwards, and a wedged first dispatch must trip
                # serve-stalled like any other startup wedge instead of
                # hanging warmup forever
                self._canary_baseline(fams)
        finally:
            if token is not None:
                self.watchdog.done(token)
        self._warm = True
        logger.info("serve: warmup took %.2fs (%s)", secs,
                    self.engine.aot.stats if self.engine.aot else "no AOT")
        return secs

    # -- SDC canary (resilience/sdc.py layer 4) ------------------------------

    def _canary_baseline(self, fams: Dict) -> None:
        """Record one golden (input, digest) pair per (workload,
        family) right after warmup — the executables are
        just-compiled/verified here, so the digest pins a healthy
        chip's bit-exact answer.  Continuous mode probes the (segment,
        warm) executable it actually serves with; FIFO mode probes the
        ladder's cheapest cold level."""
        import zlib

        from raft_tpu.resilience.sdc import param_tree_digest

        for workload, eng in self.engines.items():
            B = eng.batch_size
            wc = getattr(eng, "warm_channels", 2)
            for family, hw in fams.items():
                H, W = hw
                rng = np.random.default_rng(zlib.crc32(
                    f"sdc-canary/{workload}/{family}".encode()))
                img1 = rng.integers(0, 255,
                                    (B, H, W, 3)).astype(np.float32)
                img2 = rng.integers(0, 255,
                                    (B, H, W, 3)).astype(np.float32)
                if self.continuous:
                    iters = self._segment
                    flow_init = np.zeros((B, H // 8, W // 8, wc),
                                         np.float32)
                else:
                    iters, flow_init = self.controller.levels[-1], None
                low, up = eng.forward(hw, iters, img1, img2,
                                      flow_init=flow_init)
                self._canary[(workload, family)] = {
                    "engine": eng, "hw": hw, "iters": iters,
                    "img1": img1, "img2": img2, "flow_init": flow_init,
                    "warm": flow_init is not None,
                    "digest": param_tree_digest([low, up]),
                }

    def _maybe_canary(self) -> None:
        """Probe one (workload, family) pair when due — called from the
        batcher thread BETWEEN dispatches (idle, or right after a batch
        completed), never while client work is in flight, so the hot
        path only ever pays one small extra dispatch per
        ``canary_every`` batches.  A digest mismatch is answered
        in-place: evict the executable, recompile/reload, re-probe —
        the recheck decides whether the corruption lived in the
        executable (healed, ``recovered``) or the chip is flaky
        (``fatal``; the readiness probe flips so this replica drains)."""
        if not self.canary_every or not self._canary:
            return
        if self._batch_no - self._canary_last < self.canary_every:
            return
        self._canary_last = self._batch_no
        from raft_tpu.resilience.sdc import param_tree_digest

        keys = sorted(self._canary)
        key = keys[self._canary_rr % len(keys)]
        self._canary_rr += 1
        rec = self._canary[key]
        eng, hw, iters = rec["engine"], rec["hw"], rec["iters"]

        def probe() -> int:
            low, up = eng.forward(hw, iters, rec["img1"], rec["img2"],
                                  flow_init=rec["flow_init"])
            return param_tree_digest([low, up])

        token = None
        t_probe = self._clock()
        if self.watchdog is not None:
            # slow=True: a mismatch pays a recompile inside this bracket
            token = self.watchdog.begin(
                f"sdc canary probe {key[0]}/{key[1]} batch "
                f"{self._batch_no}", slow=True)
        try:
            self._canary_counts["probes"] += 1
            d = probe()
            if d == rec["digest"]:
                return
            self._canary_counts["mismatches"] += 1
            if eng.invalidate(hw, iters, warm=rec["warm"]):
                # count only a genuine eviction: the report's
                # "recompile-and-recheck" claim must match what ran
                self._canary_counts["recompiles"] += 1
            d2 = probe()
            label = f"{key[0]}/{key[1]}"
            if d2 == rec["digest"]:
                self._incident(
                    "sdc-serve-canary",
                    f"golden-input canary for {label} mismatched its "
                    f"baseline digest ({d:#010x} != {rec['digest']:#010x})"
                    f" at batch {self._batch_no}; executor "
                    f"recompile-and-recheck RESTORED the baseline — the "
                    f"corruption lived in the executable, now evicted; "
                    f"output served between the last clean probe and "
                    f"this one is suspect",
                    sample=False, severity="recovered")
            else:
                self._canary_failed = True
                self._incident(
                    "sdc-serve-canary",
                    f"golden-input canary for {label} mismatched its "
                    f"baseline digest ({d:#010x} != "
                    f"{rec['digest']:#010x}) and a recompiled executor "
                    f"STILL disagrees ({d2:#010x}) — this chip computes "
                    f"wrong flow; readiness flipped false so the "
                    f"replica drains instead of shipping it",
                    sample=False, severity="fatal")
        except Exception as e:  # noqa: BLE001 — a probe crash must not
            # kill the batcher thread (the silent-drop failure mode);
            # it is still loud in the log
            logger.warning("serve: sdc canary probe %s failed "
                           "(%s: %s); will retry next cadence",
                           key, type(e).__name__, e)
        finally:
            if token is not None:
                self.watchdog.done(token)
            if self.tracer is not None:
                # the probe delayed whatever dispatches next; the next
                # assembled batch's traces carry it as an annotation
                self._canary_ms_pending += \
                    (self._clock() - t_probe) * 1e3

    def submit(self, image1: np.ndarray, image2: np.ndarray,
               deadline_ms: Optional[float] = None,
               stream: Optional[str] = None,
               workload: str = "flow",
               trace_id: Optional[str] = None):
        """Admit one request; returns its Future.  Raises the typed
        :class:`RequestError` subclasses on admission rejection (also
        counted + ledgered — the caller seeing the reason IS the typed
        shed).  ``workload`` routes to that workload's executables
        ("flow" by default; e.g. "stereo" on a server built with a
        stereo engine) — an unknown workload is a typed bad-request,
        it could never be served.  ``trace_id`` joins this request to
        a trace the fleet front door already opened (same id on both
        ledgers is the merge join key)."""
        deadline = (self._clock() + deadline_ms / 1000.0
                    if deadline_ms is not None else None)
        tr = (self.tracer.begin(rid=None, stream=stream,
                                workload=workload, tid=trace_id)
              if self.tracer is not None else None)
        # submitted and its admission outcome land under ONE lock hold
        # (queue.submit's own lock nests safely below): a close()-time
        # conservation snapshot must never observe a submit between the
        # two increments and declare a spurious silent drop
        with self._lock:
            self.counters["submitted"] += 1
            try:
                if workload not in self.engines:
                    raise BadRequestError(
                        f"unknown workload {workload!r} (this server "
                        f"serves: {sorted(self.engines)})")
                req = self.queue.submit(image1, image2,
                                        deadline=deadline,
                                        stream=stream,
                                        workload=workload,
                                        clock=self._clock)
            except RequestError as e:
                key = ("rejected_queue_full" if e.kind == "queue-full"
                       else "rejected_bad_request")
                self.counters[key] += 1
                rejected = e
            else:
                rejected = None
        if rejected is not None:
            if tr is not None:
                self.tracer.finish(tr, f"rejected:{rejected.kind}")
            self._incident(rejected.kind, str(rejected))
            raise rejected
        if tr is not None:
            tr.rid = req.rid
            tr.family = req.family
            tr.stamp("admit")
            req.trace = tr
        return req.future

    # -- probes --------------------------------------------------------------

    def ready(self) -> bool:
        """Readiness: executables warm, batcher alive, watchdog clean,
        and the SDC canary has not condemned this chip."""
        return (self._warm and self._thread.is_alive()
                and not self._canary_failed
                and (self.watchdog is None or self.watchdog.tripped is None))

    def health(self) -> Dict:
        """Liveness + load snapshot (the probe payload)."""
        return {
            "ok": self._thread.is_alive()
                  and (self.watchdog is None
                       or self.watchdog.tripped is None),
            "ready": self.ready(),
            "canary_failed": self._canary_failed,
            "queue_depth": len(self.queue),
            "queue_capacity": self.queue.capacity,
            "degradation_level": self.controller.level,
            "iters": self.controller.iters,
            "counters": dict(self.counters),
        }

    # -- batcher thread ------------------------------------------------------

    def _reject(self, req, err: RequestError, counter_key: str) -> None:
        with self._lock:
            self.counters[counter_key] += 1
        if self.tracer is not None and req.trace is not None:
            # terminal BEFORE the incident write: the rejected trace
            # must sit completed in the flight-recorder ring when the
            # incident's flush walks it
            self.tracer.finish(req.trace, f"rejected:{err.kind}")
        self._incident(err.kind, str(err))
        if not req.future.set_running_or_notify_cancel():
            return
        req.future.set_exception(err)

    def _warm_inits(self, kept, hw, engine):
        """Per-slot warm-start init from each stream's previous low-res
        output: flow streams forward-splat it (the paper's video warm
        start); 1-channel workloads (stereo disparity) reuse it as-is —
        disparity carries no transport field to splat along.  Zero for
        cold slots (numerically the cold start).  Returns
        ``(warm_init, warm_slots)`` with ``warm_init`` None when NO
        slot is warm (pure-cold batches use the cold executable) and
        ``warm_slots`` the slot indices that actually GOT warm state —
        the per-slot truth the result's ``warm`` flag reports (a cold
        stream batched next to a warm neighbor is still cold).  A
        stream whose stored state came from a DIFFERENT bucket family
        (the client changed frame size mid-stream) is dropped and
        cold-starts — a shape-mismatched warm init must never kill the
        batcher."""
        H, W = hw
        B = engine.batch_size
        wc = getattr(engine, "warm_channels", 2)
        any_warm = False
        warm_slots = set()
        warm_init = np.zeros((B, H // 8, W // 8, wc), np.float32)
        for i, req in enumerate(kept):
            if req is None or req.stream is None:
                continue
            warm = self._warm_state((req.workload, req.stream), hw, wc)
            if warm is None:
                continue
            warm_init[i] = warm
            any_warm = True
            warm_slots.add(i)
        return (warm_init if any_warm else None), warm_slots

    def _warm_state(self, key, hw, wc: int) -> Optional[np.ndarray]:
        """ONE stream's warm-start init ((H/8, W/8, wc) splatted state)
        or None when it is cold — the single-key lookup both batchers
        share (the continuous admission path calls this per joiner; a
        full-batch assembly would allocate and scan B slots to warm
        one)."""
        from raft_tpu.ops import forward_interpolate

        H, W = hw
        prev = self._streams.get(key)
        if prev is None and self.spill_store is not None:
            # fleet adoption: this stream last ran on ANOTHER
            # replica (death, drain, or ring move) and spilled its
            # warm state through the shared store — a verified load
            # continues the video warm-start chain; a miss or a
            # corrupt entry is the typed re-cold-start (the store
            # fires fleet-cold-start itself on corruption)
            prev = self.spill_store.get(key)
            if prev is not None and prev.shape == (H // 8, W // 8, wc):
                self._streams[key] = prev
                self._streams.move_to_end(key)
                self._incident(
                    "fleet-warm-adopt",
                    f"stream {key[0]}/{key[1]} warm state "
                    f"adopted from the spill store (verified); video "
                    f"warm-start chain continues across the replica "
                    f"change")
        if prev is None:
            return None
        if prev.shape != (H // 8, W // 8, wc):
            self._streams.pop(key, None)
            return None
        return forward_interpolate(prev) if wc == 2 else prev

    def _remember_stream(self, key, low: np.ndarray) -> None:
        """``key`` is (workload, stream id): two workloads' client
        stream namespaces must not collide on warm state."""
        self._streams[key] = low
        self._streams.move_to_end(key)
        while len(self._streams) > self._max_streams:
            self._streams.popitem(last=False)
        if self.spill_store is not None:
            try:
                self.spill_store.put(key, low)
            except OSError:
                # a full/unwritable spill disk costs only the WARM
                # adoption after a future replica change (that stream
                # re-cold-starts typed); it must never fail the request
                logger.warning("serve: spill of stream %s/%s failed; "
                               "a replica change will cold-start it",
                               key[0], key[1])

    def _serve_loop(self) -> None:
        B = self.engine.batch_size
        while not self._stop.is_set():
            with self.spans.span("queue"):
                reqs = self.queue.pop_batch(B, timeout=0.05)
            if not reqs:
                self._maybe_canary()
                continue
            self._batch_no += 1
            try:
                self._process_batch(reqs, B)
            except Exception as e:  # noqa: BLE001 — the batcher thread
                # must survive ANY per-batch failure: a dead batcher
                # strands every pending future forever, the exact
                # silent-drop failure this layer exists to kill.  The
                # batch's own requests are rejected typed instead.
                logger.exception("serve: batch %d processing failed",
                                 self._batch_no)
                err = BadRequestError(
                    f"batch {self._batch_no} processing failed "
                    f"({type(e).__name__}: {e})")
                for req in reqs:
                    if not req.future.done():
                        self._reject(req, err, "rejected_bad_request")
            # canary cadence check between dispatches: the just-served
            # batch's futures are already resolved, so a due probe
            # never adds latency to work a client is waiting on
            self._maybe_canary()
            if self._batch_no % self._flush_every == 0:
                try:
                    self.spans.flush(self._batch_no)
                except (ValueError, OSError):
                    # unwritable/closed ledger: telemetry must never
                    # kill the batcher (the silent-drop failure mode)
                    logger.warning("serve: span flush failed at batch "
                                   "%d; continuing", self._batch_no)

    def _admit_assemble(self, reqs, B: int):
        """The admission prologue BOTH batcher modes share: assemble
        the padded batch (typed deadline/poison rejections routed),
        take the controller's iteration decision under the current
        pressure (which includes the just-popped batch: with max_batch
        close to capacity the post-pop depth alone could never reach
        the high watermark even at saturation), and build the per-slot
        warm inits.  Returns None when nothing survived admission."""
        workload = reqs[0].workload
        family = reqs[0].family
        engine = self.engines[workload]
        hw = self.buckets[family]
        if self.tracer is not None:
            canary_ms, self._canary_ms_pending = \
                self._canary_ms_pending, 0.0
            for req in reqs:
                if req.trace is not None:
                    # the pop closes the queue-wait phase; a preceding
                    # canary probe annotates the batch it delayed
                    req.trace.stamp("queue-wait")
                    if canary_ms:
                        req.trace.event("canary-interleave",
                                        ms=round(canary_ms, 3))
        with self.spans.span("batch"):
            img1, img2, kept, rejected = assemble_batch(
                reqs, hw, B, clock=self._clock)
        for req, err in rejected:
            self._reject(req, err,
                         "rejected_deadline"
                         if err.kind == "deadline-exceeded"
                         else "rejected_bad_request")
        if not any(r is not None for r in kept):
            return None
        frac = min(1.0, (len(self.queue) + len(reqs))
                   / self.queue.capacity)
        iters = self.controller.observe(frac,
                                        self.latency.rolling_p95_ms())
        warm_init, warm_slots = self._warm_inits(kept, hw, engine)
        if self.tracer is not None:
            for req in kept:
                if req is not None and req.trace is not None:
                    req.trace.stamp("assembly")
        return {"workload": workload, "family": family,
                "engine": engine, "hw": hw, "img1": img1, "img2": img2,
                "kept": kept, "iters": iters, "warm_init": warm_init,
                "warm_slots": warm_slots}

    def _process_batch(self, reqs, B: int) -> None:
        adm = self._admit_assemble(reqs, B)
        if adm is None:
            self.spans.step_boundary()
            return
        workload, family = adm["workload"], adm["family"]
        engine, hw = adm["engine"], adm["hw"]
        img1, img2, kept = adm["img1"], adm["img2"], adm["kept"]
        iters, flow_init = adm["iters"], adm["warm_init"]
        warm_slots = adm["warm_slots"]
        if flow_init is not None and self.warm_iters is not None \
                and all(r is None or i in warm_slots
                        for i, r in enumerate(kept)):
            # fully-warm video batch: flow_init starts the GRU at
            # last frame's solution, so the flat region extends
            # further down the ladder.  The FIFO batch runs ONE
            # iteration count for every slot, so the clamp applies
            # only when ALL slots are warm (continuous mode clamps
            # per-slot — each slot carries its own budget there).
            iters = min(iters, self.warm_iters)

        token = None
        traced = ([r for r in kept
                   if r is not None and r.trace is not None]
                  if self.tracer is not None else [])
        lazy = not engine.is_compiled(
            hw, iters, warm=flow_init is not None)
        if self.watchdog is not None:
            # a not-yet-memoized executable pays a lazy compile (or
            # cache load) inside this bracket: grant it the compile
            # bound, not the dispatch bound
            token = self.watchdog.begin(
                f"dispatch batch {self._batch_no} "
                f"workload={workload} family={family} "
                f"iters={iters} warm={flow_init is not None}"
                + (" +compile" if lazy else ""), slow=lazy)
        fb0 = getattr(engine, "fallbacks", None)
        try:
            if traced and lazy:
                # split compile from run for attribution: memoize the
                # executable first (same work forward would trigger),
                # then charge the build to its own phase
                engine.executable(hw, iters, warm=flow_init is not None)
                for req in traced:
                    req.trace.stamp("compile")
            flow_low, flow_up = engine.forward(
                hw, iters, img1, img2, flow_init=flow_init)
        except Exception as e:  # noqa: BLE001 — a dispatch failure
            # must reject ITS requests typed, not kill the server
            if token is not None:
                self.watchdog.done(token)
            err = BadRequestError(
                f"dispatch failed ({type(e).__name__}: {e})")
            for req in kept:
                if req is not None:
                    self._reject(req, err, "rejected_bad_request")
            return
        if token is not None:
            self.watchdog.done(token)
        for req in traced:
            req.trace.stamp("dispatch")
            if fb0 is not None and engine.fallbacks > fb0:
                # the q8 tripwire re-dispatched this batch on the bf16
                # twin inside forward — the dispatch phase carries both
                req.trace.event("q8-fallback")

        now = self._clock()
        fam_label = f"{workload}/{family}"
        for i, req in enumerate(kept):
            if req is None:
                continue
            h, w = req.hw
            if req.stream is not None:
                self._remember_stream((req.workload, req.stream),
                                      flow_low[i])
            with self._lock:
                self.counters["served"] += 1
                self.counters["batches"] = self._batch_no
                fc = self._family_counts.setdefault(
                    fam_label, {"served": 0, "batches": 0})
                fc["served"] += 1
            self.latency.add(now - req.t_submit)
            self._family_latency.setdefault(
                fam_label, LatencyTracker()).add(now - req.t_submit)
            if req.future.set_running_or_notify_cancel():
                req.future.set_result(
                    {"flow": flow_up[i, :h, :w, :],
                     "flow_low": flow_low[i],
                     "iters": iters,
                     # per-SLOT truth: a cold stream batched next to a
                     # warm neighbor did NOT warm-start
                     "warm": i in warm_slots})
            if self.tracer is not None and req.trace is not None:
                self.tracer.finish(req.trace, "served")
        with self._lock:
            if fam_label in self._family_counts:
                self._family_counts[fam_label]["batches"] += 1
        self.spans.step_boundary()

    # -- continuous batching -------------------------------------------------
    #
    # The FIFO batcher above holds an ASSEMBLY BARRIER: a batch's slots
    # are fixed at pop time and ride together for the full iteration
    # depth, so a request arriving one instant after assembly waits out
    # an entire 32-iteration dispatch even when the batch has empty
    # slots.  The GRU refinement loop has natural yield points — the
    # iteration boundaries — and the warm executable (flow_init) makes
    # them schedulable: running `segment_iters` at a time and re-feeding
    # flow_low as the next segment's flow_init is exactly the paper's
    # video warm-start semantics applied WITHIN one request.  At every
    # boundary, freed/empty slots admit new requests from the same
    # (workload, family) lane.  Slot contents are independent within
    # one executable (the PR 10 poison-isolation proof), so admitting a
    # joiner leaves every other slot's outputs BIT-identical to the
    # unjoined run — test-pinned in tests/test_fleet.py.

    def _begin_inflight(self, reqs, B: int):
        """Assemble the first segment's batch; returns the in-flight
        state dict or None when nothing survived admission checks.
        Slot iteration budgets round UP to whole segments (a level of
        6 at segment_iters=4 runs 8) — the executed count is what the
        result's ``iters`` reports."""
        adm = self._admit_assemble(reqs, B)
        if adm is None:
            return None
        engine, hw = adm["engine"], adm["hw"]
        kept, iters = adm["kept"], adm["iters"]
        warm_slots = adm["warm_slots"]
        H, W = hw
        wc = getattr(engine, "warm_channels", 2)
        flow = adm["warm_init"]
        if flow is None:
            flow = np.zeros((B, H // 8, W // 8, wc), np.float32)
        remaining = [0] * B
        for i, r in enumerate(kept):
            if r is not None:
                t = iters
                if self.warm_iters is not None and i in warm_slots:
                    t = min(t, self.warm_iters)
                remaining[i] = t
        return {"lane": (adm["workload"], adm["family"]),
                "engine": engine, "hw": hw,
                "img1": adm["img1"], "img2": adm["img2"], "flow": flow,
                "slots": kept, "remaining": remaining,
                "warm": warm_slots, "segments": [0] * B}

    def _admit_inflight(self, state, free) -> None:
        """Fill free slots from the in-flight lane's queue at a segment
        boundary — the continuous-batching admission.  A request popped
        here MUST reach a terminal state (seated or typed reject): an
        unseated pop is a silent drop, the exact conservation violation
        this layer exists to kill."""
        from raft_tpu.serve.batcher import slot_is_finite
        from raft_tpu.serve.engine import pad_to_bucket

        reqs = self.queue.pop_lane(state["lane"], len(free))
        if not reqs:
            return
        if self.tracer is not None:
            for req in reqs:
                if req.trace is not None:
                    req.trace.stamp("queue-wait")
        # the admission boundary is the continuous-mode analogue of the
        # FIFO path's batch assembly: under sustained traffic the
        # in-flight batch never empties, so without this observe() the
        # degradation controller would freeze at whatever level the
        # FIRST assembly saw, no matter how far queue pressure or p95
        # drift afterwards
        frac = min(1.0, (len(self.queue) + len(reqs))
                   / self.queue.capacity)
        iters = self.controller.observe(frac,
                                        self.latency.rolling_p95_ms())
        hw = state["hw"]
        engine = state["engine"]
        wc = getattr(engine, "warm_channels", 2)
        now = self._clock()
        it = iter(free)
        for req in reqs:
            i = None
            try:
                if req.deadline is not None and now > req.deadline:
                    self._reject(req, DeadlineExceededError(
                        f"request {req.rid} expired before joining the "
                        f"in-flight batch (deadline-aware shed at the "
                        f"iteration boundary)"), "rejected_deadline")
                    continue
                if not slot_is_finite(req):
                    self._reject(req, BadRequestError(
                        f"request {req.rid} carries non-finite input "
                        f"pixels; rejected at the iteration boundary — "
                        f"its slot stays zero, neighbors unaffected"),
                        "rejected_bad_request")
                    continue
                i = next(it)
                state["img1"][i] = pad_to_bucket(
                    req.image1.astype(np.float32), hw)
                state["img2"][i] = pad_to_bucket(
                    req.image2.astype(np.float32), hw)
                # the joiner's warm start: its stream's spilled or
                # remembered state when available, zeros (cold) otherwise
                state["flow"][i] = 0.0
                if req.stream is not None:
                    warm = self._warm_state((req.workload, req.stream),
                                            hw, wc)
                    if warm is not None:
                        state["flow"][i] = warm
                        state["warm"].add(i)
                t = iters
                if self.warm_iters is not None and i in state["warm"]:
                    t = min(t, self.warm_iters)
                state["slots"][i] = req
                state["remaining"][i] = t
                state["segments"][i] = 0
                if req.trace is not None:
                    req.trace.stamp("assembly")
                    req.trace.event("joined-inflight", slot=i)
            except Exception as e:  # noqa: BLE001 — a failed seat
                # rejects THAT request typed and restores its slot to
                # the empty-pad contract (zero images, zero flow); the
                # remaining popped requests still get their admission
                logger.exception("serve: continuous admission of %s "
                                 "failed", req.rid)
                if i is not None:
                    state["img1"][i] = 0.0
                    state["img2"][i] = 0.0
                    state["flow"][i] = 0.0
                    state["warm"].discard(i)
                    state["slots"][i] = None
                self._reject(req, BadRequestError(
                    f"request {req.rid} failed continuous admission "
                    f"({type(e).__name__}: {e})"), "rejected_bad_request")

    def _dispatch_segment(self, state) -> None:
        """Run ONE `segment_iters` segment of the in-flight batch and
        complete the slots whose iteration budget is spent."""
        engine = state["engine"]
        hw = state["hw"]
        seg = self._segment
        token = None
        traced = ([r for r in state["slots"]
                   if r is not None and r.trace is not None]
                  if self.tracer is not None else [])
        lazy = not engine.is_compiled(hw, seg, warm=True)
        if self.watchdog is not None:
            token = self.watchdog.begin(
                f"continuous segment batch {self._batch_no} "
                f"lane={state['lane']} seg={seg}"
                + (" +compile" if lazy else ""), slow=lazy)
        fb0 = getattr(engine, "fallbacks", None)
        try:
            if traced and lazy:
                engine.executable(hw, seg, warm=True)
                for req in traced:
                    req.trace.stamp("compile")
            flow_low, flow_up = engine.forward(
                hw, seg, state["img1"], state["img2"],
                flow_init=state["flow"])
        except Exception as e:  # noqa: BLE001 — a dispatch failure
            # rejects ITS slots typed, never kills the batcher
            if token is not None:
                self.watchdog.done(token)
            err = BadRequestError(
                f"continuous dispatch failed ({type(e).__name__}: {e})")
            for i, req in enumerate(state["slots"]):
                if req is not None:
                    self._reject(req, err, "rejected_bad_request")
                    state["slots"][i] = None
            return
        if token is not None:
            self.watchdog.done(token)
        state["flow"] = np.asarray(flow_low).copy()
        now = self._clock()
        for i, req in enumerate(state["slots"]):
            if req is None:
                continue
            state["remaining"][i] -= seg
            state["segments"][i] += 1
            if req.trace is not None and self.tracer is not None:
                # per-segment iteration span: each boundary charges the
                # segment's wall to the dispatch phase and annotates it
                req.trace.stamp("dispatch")
                req.trace.event("segment",
                                n=state["segments"][i], iters=seg)
                if fb0 is not None and engine.fallbacks > fb0:
                    req.trace.event("q8-fallback")
            if state["remaining"][i] > 0:
                continue
            # slot complete: deliver, remember the stream, free it
            h, w = req.hw
            fam_label = f"{req.workload}/{state['lane'][1]}"
            flow_low_i = state["flow"][i].copy()
            if req.stream is not None:
                self._remember_stream((req.workload, req.stream),
                                      flow_low_i)
            with self._lock:
                self.counters["served"] += 1
                self.counters["batches"] = self._batch_no
                fc = self._family_counts.setdefault(
                    fam_label, {"served": 0, "batches": 0})
                fc["served"] += 1
                fc["batches"] += 1
            self.latency.add(now - req.t_submit)
            self._family_latency.setdefault(
                fam_label, LatencyTracker()).add(now - req.t_submit)
            if req.future.set_running_or_notify_cancel():
                req.future.set_result(
                    {"flow": np.asarray(flow_up)[i, :h, :w, :],
                     "flow_low": flow_low_i,
                     # the EXECUTED count: budgets round up to whole
                     # segments, and reporting the smaller requested
                     # number would misattribute the latency paid
                     "iters": state["segments"][i] * seg,
                     "segments": state["segments"][i],
                     "warm": i in state["warm"]})
            if self.tracer is not None and req.trace is not None:
                self.tracer.finish(req.trace, "served")
            state["slots"][i] = None
            state["warm"].discard(i)
            # freed slot back to the empty-pad shape: zero images and
            # zero flow state, exactly what an unjoined run carries
            state["img1"][i] = 0.0
            state["img2"][i] = 0.0
            state["flow"][i] = 0.0

    def _serve_loop_continuous(self) -> None:
        B = self.engine.batch_size
        state = None
        while True:
            if state is None:
                if self._stop.is_set():
                    return
                with self.spans.span("queue"):
                    reqs = self.queue.pop_batch(B, timeout=0.05)
                if not reqs:
                    # between in-flight batches: the one place the
                    # continuous loop is provably not holding client
                    # slots, so the canary probes here
                    self._maybe_canary()
                    continue
                self._batch_no += 1
                try:
                    state = self._begin_inflight(reqs, B)
                except Exception as e:  # noqa: BLE001 — survive any
                    # per-batch failure (see _serve_loop)
                    logger.exception("serve: continuous batch %d "
                                     "assembly failed", self._batch_no)
                    err = BadRequestError(
                        f"batch {self._batch_no} assembly failed "
                        f"({type(e).__name__}: {e})")
                    for req in reqs:
                        if not req.future.done():
                            self._reject(req, err,
                                         "rejected_bad_request")
                    state = None
                if state is None:
                    continue
            elif not self._stop.is_set():
                free = [i for i, s in enumerate(state["slots"])
                        if s is None]
                # fairness: while ANOTHER (workload, family) lane has
                # queued work, stop admitting same-lane joiners and let
                # the in-flight batch DRAIN — admission-only-from-own-
                # lane would otherwise starve every other lane forever
                # under sustained traffic (the drained batch frees the
                # executable within the slots' remaining segment
                # budgets, then pop_batch serves the oldest lane head)
                if free and not self.queue.other_lane_waiting(
                        state["lane"]):
                    try:
                        self._admit_inflight(state, free)
                    except Exception:  # noqa: BLE001 — a failed
                        # admission must not kill the in-flight batch
                        logger.exception("serve: continuous admission "
                                         "failed; continuing in-flight")
                self._batch_no += 1
            try:
                self._dispatch_segment(state)
            except Exception as e:  # noqa: BLE001 — reject the batch
                # typed and drop it; the loop itself must survive
                logger.exception("serve: continuous segment %d failed",
                                 self._batch_no)
                err = BadRequestError(
                    f"segment {self._batch_no} failed "
                    f"({type(e).__name__}: {e})")
                for i, req in enumerate(state["slots"]):
                    if req is not None and not req.future.done():
                        self._reject(req, err, "rejected_bad_request")
                state = None
                continue
            if not any(s is not None for s in state["slots"]):
                state = None
                self.spans.step_boundary()
                self._maybe_canary()
            if self._batch_no % self._flush_every == 0:
                try:
                    self.spans.flush(self._batch_no)
                except (ValueError, OSError):
                    logger.warning("serve: span flush failed at batch "
                                   "%d; continuing", self._batch_no)

    # -- shutdown ------------------------------------------------------------

    def serving_summary(self) -> Dict:
        """The ``run_end`` serving section (also the CLI's JSON line)."""
        with self._lock:
            counters = dict(self.counters)
        rejected = (counters["rejected_queue_full"]
                    + counters["rejected_deadline"]
                    + counters["rejected_bad_request"]
                    + counters["rejected_shutdown"])
        summary = {
            **counters,
            "rejected_total": rejected,
            "unaccounted": counters["submitted"] - counters["served"]
                           - rejected,
            **self.latency.percentiles_ms(),
            # bounded quantile sketch of the latency reservoir: the
            # fleet merge path (obs report --merge) pools these across
            # replicas to compute a genuine fleet-wide p95 — summed
            # counters cannot recover a percentile
            "latency_samples_ms": self.latency.sample_ms(),
            "slo_p95_ms": self.slo_ms,
            "degradation": self.controller.summary(),
        }
        # per-(workload, family) attribution: the obs report renders
        # one latency/throughput row per family, so flow and stereo
        # traffic stay separable (a slow family cannot hide inside the
        # pooled percentiles)
        families = {}
        for label, fc in sorted(self._family_counts.items()):
            row = dict(fc)
            lat = self._family_latency.get(label)
            if lat is not None:
                row.update(lat.percentiles_ms())
            families[label] = row
        if families:
            summary["families"] = families
        if self.canary_every:
            summary["canary"] = dict(self._canary_counts) | {
                "families": len(self._canary)}
        if self.engine.aot is not None:
            summary["aot_cache"] = dict(self.engine.aot.stats)
        if self.tracer is not None:
            # the percentiles above become addressable: each bucket
            # names one concrete (force-retained) trace id, so "p95
            # moved" always has a request to open with --trace
            summary["trace"] = {
                **self.tracer.summary(),
                "exemplars": self.tracer.exemplars({
                    "p50": summary.get("latency_p50_ms"),
                    "p95": summary.get("latency_p95_ms"),
                    "max": summary.get("latency_max_ms")}),
            }
        return summary

    def kill(self, timeout: float = 60.0):
        """Crash-style stop — the fleet's kill-a-replica path.

        Unlike :meth:`close`, nothing waits for the queue to drain and
        no summary/run_end is written (a real crash writes nothing):
        the batcher stops after its in-flight work, the watchdog is
        disarmed, and everything still QUEUED is returned to the caller
        — the fleet front door re-routes those requests to surviving
        replicas (the typed rescue), so a replica death is never a
        silent drop.  The returned requests remain un-rejected here:
        their terminal outcome is the FLEET's to decide.
        """
        self._stop.set()
        self._thread.join(timeout=timeout)
        if self.watchdog is not None:
            self.watchdog.stop()
        return self.queue.drain()

    def close(self, timeout: float = 10.0) -> Dict:
        """Stop the batcher, reject everything still queued (typed),
        write the serving summary, return it."""
        deadline = self._clock() + timeout
        while len(self.queue) and self._clock() < deadline:
            time.sleep(0.01)
        self._stop.set()
        # wait out an in-flight compile/dispatch: the summary's
        # conservation counters must be FINAL, not racing the batcher's
        # last future resolutions (a wedged dispatch is the watchdog's
        # job, not close's)
        self._thread.join(timeout=max(timeout, 60.0))
        for req in self.queue.drain():
            self._reject(req, BadRequestError(
                f"request {req.rid} still queued at shutdown; rejected "
                f"typed (no silent drops)"), "rejected_shutdown")
        if self.watchdog is not None:
            self.watchdog.stop()
        summary = self.serving_summary()
        if summary["unaccounted"]:
            # the conservation law is the no-silent-drops proof; a
            # violation is its own FATAL kind so the chaos gate
            # (--fail-on-incident fatal) trips on it — 'bad-request' is
            # a client-input rejection and only warns
            self._incident(
                "serve-conservation",
                f"request conservation violated at close: "
                f"{summary['unaccounted']} request(s) unaccounted for "
                f"(submitted != served + rejected — a silent drop)",
                sample=False)
        if self.tracer is not None:
            # final flight-recorder window: the last completed traces
            # survive to the ledger even when nothing forced them
            self.tracer.close()
        if self.ledger is not None:
            try:
                self.spans.flush(self._batch_no)
                self.ledger.close(summary={"serving": summary})
            except (ValueError, OSError):
                # a full disk must not eat the summary the caller is
                # owed — the ledger just loses its run_end record
                logger.warning("serve: final ledger flush/close failed")
        return summary
