"""CLI driver: ``python -m raft_tpu.serve`` — a synthetic serving
session against the real FlowServer.

No network surface (the subsystem is the queue/batcher/executor
composition; transport is deployment-specific) — the driver generates
synthetic request traffic in-process, which is exactly what the chaos
matrix (scripts/chaos_dryrun.py --serve), the serving bench lane
(bench.py) and the README quickstart need: a fully-driven server with
every failure injection reachable from flags.

Prints TWO machine-readable lines on stdout:

- after warmup: ``{"serve_startup": {"startup_s": ..., "warm_hits":
  ..., "cold_compiles": ...}}`` — flushed immediately, so a SIGKILLed
  session still reports its startup cost (the warm-restart gate's
  measurement);
- at exit: ``{"serve_summary": {...}}`` — the serving summary (request
  conservation counters, latency percentiles vs SLO, degradation
  history, AOT cache stats).

Exit codes: 0 clean; 1 when ``--fail-on-slo`` trips or request
conservation is violated; 14 (:data:`SERVE_WATCHDOG_EXIT_CODE`) when
the dispatch watchdog declares a wedge; 2 usage.

``--inject`` (serve-side chaos, distinct from the training-path
``--inject`` grammar in resilience/faults.py):

- ``overload``       submit the whole load as one burst against the
                     bounded queue: typed ``queue-full`` sheds
- ``deadline-storm`` every request carries a ~0 deadline: typed
                     ``deadline-exceeded`` rejections pre-dispatch
- ``poison@K``       request K ships non-finite pixels: typed
                     ``bad-request``, neighbors unaffected
- ``sigkill@K``      hard-kill the process (SIGKILL, no cleanup) after
                     K served requests: the crash the AOT cache must
                     survive
- ``stall``          wedge the first dispatch forever: the watchdog
                     must convert the hang into ``serve-stalled`` +
                     exit 14 (pair with --watchdog_timeout)
- ``canary-flip``    after warmup, the flow engine starts scaling its
                     outputs by 1+1e-3 (finite, silent — a flaky chip)
                     until an executor recompile heals it: the SDC
                     canary (--canary_every) must catch the digest
                     mismatch, recompile-and-recheck, and record a
                     recovered ``sdc-serve-canary``
- ``quant-overflow@K`` (needs ``--quantize``) the Kth batch dispatch
                     after warmup carries pixels far outside the int8
                     calibration premise: the runtime range tripwire
                     must fire, the request must be RE-SERVED on the
                     bf16 executable (typed, recovered
                     ``serve-quant-fallback``), and conservation must
                     hold — quantization degrades typed, never wrong

``--trace_sample N`` (needs ``--ledger``) threads a per-request trace
context through the whole serve path — admission, queue wait, batch
assembly, compile-vs-run, dispatch; under ``--fleet`` also the front
door's place/reroute/replica-wait and every hop — head-sampled 1-in-N
with forced retention of typed rejections, SLO violators and
incident-adjacent requests (obs/trace.py; ``obs report`` renders tail
attribution, ``--trace <tid>`` a single request's cross-ledger
timeline).  0 disables tracing entirely.

``--quantize`` serves the flow workload on the int8 path
(serve/quant.py QuantServeEngine): int8 weight codes + int8 corr
contraction, certified by graftlint engine 7 against the ``quant``
calibration ledger, with a runtime range tripwire that falls back
typed to the bf16 executable when an input leaves the calibrated
envelope.

``--stereo_every N`` makes the session heterogeneous: every Nth
request routes to a stereo disparity engine (workloads/stereo.py)
through the SAME server — per-(workload, family) batching, one queue,
one degradation controller; the summary's ``families`` section carries
the per-workload split.

``--fleet N`` runs the session against N replicas behind the fleet
front door (serve/fleet.py): stream-affinity routing, a shared AOT
cache (replica 0 compiles, the rest and every restart load warm), a
shared spill store, per-replica ledgers at ``<ledger>.p<i>`` (render
with ``obs report --merge``), and a ``fleet_summary`` JSON line.  Two
fleet-only injects:

- ``kill-replica@K``   after K served requests, hard-kill the
                       busiest replica: queued work re-places typed on
                       survivors, its streams re-route and adopt
                       spilled warm state
- ``rolling-restart[@K]`` start a zero-downtime rolling restart
                       (drain -> close -> warm AOT restore -> rejoin,
                       one replica at a time) while the load runs; the
                       summary's steady_p95_ms / post_event_p95_ms
                       carry the p95-flat-through-the-roll measurement

``--continuous`` switches every server (single or fleet) to
continuous batching: requests join in-flight batches at GRU iteration
boundaries (``--segment_iters`` per segment) instead of waiting out
FIFO assembly barriers.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time


def parse_inject(spec):
    """(kind, arg) from the serve chaos grammar above."""
    if not spec:
        return None, 0
    kind, _, arg = spec.partition("@")
    kinds = ("overload", "deadline-storm", "poison", "sigkill", "stall",
             "kill-replica", "rolling-restart", "canary-flip",
             "quant-overflow")
    if kind not in kinds:
        raise ValueError(f"unknown serve inject {kind!r} "
                         f"(known: {', '.join(kinds)})")
    if kind in ("poison", "sigkill", "kill-replica"):
        if not arg.isdigit():
            raise ValueError(f"inject {kind} needs @K (request ordinal)")
        return kind, int(arg)
    if kind == "quant-overflow":
        if not arg.isdigit() or int(arg) < 1:
            raise ValueError("inject quant-overflow needs @K (batch "
                             "dispatch ordinal, 1-based)")
        return kind, int(arg)
    if kind == "rolling-restart":
        if arg and not arg.isdigit():
            raise ValueError("inject rolling-restart takes an optional "
                             "@K (served ordinal to start the roll at)")
        return kind, int(arg) if arg else 0
    if arg:
        raise ValueError(f"inject {kind} takes no @arg")
    return kind, 0


def _stereo_engine_builder(init_img, seed: int, batch_size: int, aot):
    """ONE stereo serving recipe for both session shapes: the fleet
    factory and the single-server session must serve the SAME audited
    stereo graph (model config, cache tag, warm channels) — two
    hand-copied construction blocks would silently drift, and the
    fleet's AOT cache entries would stop matching the registered
    ``stereo_serve`` entry.  Inits the model once; the returned
    closure builds one ServeEngine per call (the fleet factory calls
    it per replica)."""
    import jax

    from raft_tpu.serve.engine import ServeEngine
    from raft_tpu.workloads.stereo import (STEREO_SERVE_OVERRIDES,
                                           StereoRAFT,
                                           compile_stereo_forward,
                                           stereo_config)

    model = StereoRAFT(stereo_config(small=True,
                                     overrides=STEREO_SERVE_OVERRIDES))
    variables = model.init(jax.random.PRNGKey(seed + 1), init_img,
                           init_img, iters=2, train=True)

    def make():
        return ServeEngine(model, variables, batch_size=batch_size,
                           aot_cache=aot,
                           compile_fn=compile_stereo_forward,
                           cache_tag="stereo_serve", warm_channels=1)

    return make


def run_load(args, inject, inject_arg, hw, submit, on_served,
             after_chunk=None):
    """The synthetic load loop every session shape shares (single
    server and fleet — the duplicated ~70-line driver PR 14 recorded as
    known debt, folded here).  Builds each request deterministically
    from ``--seed`` (frames, poison placement, stream assignment,
    stereo routing, deadline storm), submits through ``submit(img1,
    img2, deadline_ms, stream, workload)`` — typed admission rejections
    are already counted by the server and simply skipped here — and
    reaps completed futures chunk-wise in paced mode (calling
    ``on_served(latency_s)`` per success) or all at the end under
    ``--inject overload``.  ``after_chunk`` runs after each paced reap
    (the fleet's chaos-event hook)."""
    import numpy as np

    from raft_tpu.serve import RequestError

    H, W = hw
    rng = np.random.default_rng(args.seed)
    futures = []
    reaped = 0

    def frame():
        return rng.integers(0, 255, (H, W, 3)).astype(np.float32)

    def reap(upto):
        nonlocal reaped
        for f, t_sub in futures[reaped:upto]:
            if f is None:
                continue
            try:
                f.result(timeout=600)
            except RequestError:
                continue
            on_served(time.perf_counter() - t_sub)
        reaped = max(reaped, upto)

    for i in range(args.requests):
        img1, img2 = frame(), frame()
        if inject == "poison" and i == inject_arg:
            img1 = img1.copy()
            img1[0, 0, 0] = np.nan
        stream = (f"s{i % args.video_streams}"
                  if args.video_streams else None)
        workload = ("stereo" if args.stereo_every
                    and (i % args.stereo_every) == args.stereo_every - 1
                    else "flow")
        deadline = args.deadline_ms
        if inject == "deadline-storm":
            deadline = -1.0            # already expired at submit: the
            # assembly/boundary deadline check MUST shed it pre-dispatch
            # regardless of how fast the batcher wakes
        try:
            futures.append((submit(img1, img2, deadline, stream,
                                   workload), time.perf_counter()))
        except RequestError:           # typed shed, already counted
            futures.append((None, 0.0))
        if inject != "overload" and (i + 1) % args.batch_size == 0:
            # paced mode: wait out the chunk so the queue never backs
            # up; overload mode slams the whole burst in at once
            reap(len(futures))
            if after_chunk is not None:
                after_chunk()
    reap(len(futures))


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        "python -m raft_tpu.serve",
        description="drive a synthetic session against the fault-"
                    "tolerant flow server")
    p.add_argument("--requests", type=int, default=16)
    p.add_argument("--image_size", type=int, nargs=2, default=(64, 64))
    p.add_argument("--batch_size", type=int, default=2)
    p.add_argument("--queue_capacity", type=int, default=16)
    p.add_argument("--iter_levels", default="8,4,2",
                   help="degradation ladder, full quality first "
                        "(production: 32,24,16,8; default is CPU-smoke "
                        "sized)")
    p.add_argument("--slo_ms", type=float, default=None,
                   help="p95 latency SLO; enables the controller's "
                        "latency signal and --fail-on-slo")
    p.add_argument("--deadline_ms", type=float, default=None,
                   help="per-request deadline")
    p.add_argument("--video_streams", type=int, default=0,
                   help="assign requests round-robin to N video streams "
                        "(flow_init warm-start chaining)")
    p.add_argument("--stereo_every", type=int, default=0,
                   help="route every Nth request to a STEREO disparity "
                        "engine through the same server (heterogeneous "
                        "per-family batching; 0 = flow only)")
    p.add_argument("--fleet", type=int, default=0,
                   help="run N FlowServer replicas behind the fleet "
                        "front door (stream-affinity routing, shared "
                        "warm-state spill store, per-replica ledgers "
                        "<ledger>.p<i>); 0 = single server.  Enables "
                        "--inject kill-replica@K / rolling-restart[@K]")
    p.add_argument("--continuous", action="store_true",
                   help="continuous batching: admit requests into "
                        "in-flight batch slots at GRU iteration "
                        "boundaries instead of FIFO assembly barriers")
    p.add_argument("--segment_iters", type=int, default=None,
                   help="iterations per continuous-batching segment "
                        "(default: the ladder's smallest level)")
    p.add_argument("--warm_iters", type=int, default=None,
                   help="iteration floor for fully-warm video batches")
    p.add_argument("--canary_every", type=int, default=0,
                   help="SDC serving canary cadence in batches: probe a "
                        "cached golden input per (workload, family) "
                        "between dispatches and compare digests "
                        "bit-exact against the warmup baseline "
                        "(resilience/sdc.py layer 4); 0 disables")
    p.add_argument("--quantize", action="store_true",
                   help="serve the flow workload on the int8 path "
                        "(serve/quant.py): int8 weight codes + int8 "
                        "corr contraction with a typed bf16 fallback "
                        "when the runtime range tripwire fires")
    p.add_argument("--no_degrade", action="store_true")
    p.add_argument("--aot_cache", default=None,
                   help="AOT executable cache directory (warm restarts)")
    p.add_argument("--ledger", default=None,
                   help="obs run-ledger path (events.jsonl)")
    p.add_argument("--trace_sample", type=int, default=16,
                   help="per-request tracing: head-sample 1-in-N traces "
                        "to the ledger (rejections, SLO violators, "
                        "incident windows and percentile exemplars are "
                        "always retained regardless).  Needs --ledger; "
                        "0 disables tracing entirely (no per-request "
                        "trace context is allocated)")
    p.add_argument("--watchdog_timeout", type=float, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--inject", default=None)
    p.add_argument("--fail-on-slo", dest="fail_on_slo",
                   action="store_true",
                   help="exit 1 when measured p95 exceeds --slo_ms")
    return p.parse_args(argv)


def fleet_main(args, inject, inject_arg) -> int:
    """The fleet session: N in-process replicas behind the front door
    (serve/fleet.py), a shared AOT cache (restarts restore warm) and a
    shared spill store (streams survive replica changes), driven by
    the same synthetic load.  Prints ``serve_startup`` after warmup and
    ``fleet_summary`` at exit; with ``--inject rolling-restart`` the
    summary carries ``steady_p95_ms`` / ``post_event_p95_ms`` — the
    client-measured p95 before vs after the event started, the
    "p95 flat through the roll" number."""
    if inject in ("sigkill", "stall"):
        print(f"serve: inject {inject} is a single-server scenario; "
              f"drop --fleet", file=sys.stderr)
        return 2

    import tempfile

    import numpy as np

    from raft_tpu.utils.platform import ensure_platform

    ensure_platform(honor_device_count_flag=False)

    import jax

    from raft_tpu.models import RAFT
    from raft_tpu.obs import RunLedger
    from raft_tpu.serve import (AOTCache, FleetServer, ServeEngine,
                                serve_config)
    from raft_tpu.serve.engine import _round8
    from raft_tpu.serve.server import FlowServer

    H, W = (_round8(x) for x in args.image_size)
    levels = tuple(int(x) for x in args.iter_levels.split(","))
    cfg = serve_config(small=True)
    model = RAFT(cfg)

    workdir = tempfile.mkdtemp(prefix="fleet_session_")
    cache_dir = args.aot_cache or os.path.join(workdir, "aot")
    ledger = None
    if args.ledger:
        ledger = RunLedger(args.ledger, meta={
            "entry": "serve-fleet", "image_size": [H, W],
            "batch_size": args.batch_size, "iter_levels": list(levels),
            "replicas": args.fleet, "slo_ms": args.slo_ms,
            "backend": jax.devices()[0].platform,
            "devices": jax.device_count(),
        })

    def fleet_incident(kind, detail):
        if ledger is not None:
            ledger.incident(kind, step=0, detail=detail)

    # ONE cache for the whole fleet: replica 0 pays the compiles, the
    # others (and every restart) verify-and-load warm
    aot = AOTCache(cache_dir, on_incident=fleet_incident)
    init_img = np.zeros((1, H, W, 3), np.float32)
    variables = model.init(jax.random.PRNGKey(args.seed), init_img,
                           init_img, iters=2, train=True)
    make_stereo = None
    if args.stereo_every:
        make_stereo = _stereo_engine_builder(init_img, args.seed,
                                             args.batch_size, aot)

    buckets = {"session": (H, W)}

    def factory(rid, spill):
        engines = {"flow": ServeEngine(model, variables,
                                       batch_size=args.batch_size,
                                       aot_cache=aot)}
        if make_stereo is not None:
            engines["stereo"] = make_stereo()
        rep_ledger = None
        rep_tracer = None
        if args.ledger:
            rep_ledger = RunLedger(
                f"{args.ledger}.p{rid[1:]}",
                meta={"entry": "serve", "replica": rid,
                      "image_size": [H, W]})
            if args.trace_sample > 0:
                from raft_tpu.obs.trace import Tracer
                rep_tracer = Tracer(rep_ledger,
                                    sample=args.trace_sample,
                                    slo_ms=args.slo_ms)
        return FlowServer(
            engines, buckets=buckets,
            queue_capacity=args.queue_capacity, iter_levels=levels,
            slo_ms=args.slo_ms, degrade=not args.no_degrade,
            warm_iters=args.warm_iters, ledger=rep_ledger,
            watchdog_timeout_s=args.watchdog_timeout,
            spill_store=spill, continuous=args.continuous,
            segment_iters=args.segment_iters,
            canary_every=args.canary_every, tracer=rep_tracer)

    tracer = None
    if ledger is not None and args.trace_sample > 0:
        from raft_tpu.obs.trace import Tracer
        # the front door carries its OWN tracer on the front ledger;
        # the replica tracers (factory above) join on the shared tid
        tracer = Tracer(ledger, sample=args.trace_sample,
                        slo_ms=args.slo_ms)
    fleet = FleetServer(factory, n_replicas=args.fleet,
                        spill_dir=os.path.join(workdir, "spill"),
                        ledger=ledger, slo_ms=args.slo_ms,
                        tracer=tracer)
    t0 = time.perf_counter()
    fleet.warmup()
    startup_s = time.perf_counter() - t0
    stats = dict(aot.stats)
    print(json.dumps({"serve_startup": {
        "startup_s": round(startup_s, 3),
        "cold_startup_s": round(fleet.cold_startup_s or 0.0, 3),
        "warm_hits": int(stats.get("hits", 0)),
        "cold_compiles": int(stats.get("misses", 0)),
        "cache_corrupt": int(stats.get("corrupt", 0)),
        "replicas": args.fleet,
    }}), flush=True)

    event_fired = [False]
    roll_thread = None
    lat_steady: list = []
    lat_after: list = []
    served = 0

    def on_served(latency_s):
        nonlocal served
        (lat_after if event_fired[0] else lat_steady).append(latency_s)
        served += 1

    def maybe_fire_event():
        nonlocal roll_thread
        if event_fired[0] or inject not in ("kill-replica",
                                            "rolling-restart"):
            return
        threshold = (inject_arg if inject_arg > 0
                     else max(args.batch_size, args.requests // 2))
        if served < threshold:
            return
        event_fired[0] = True
        if inject == "kill-replica":
            by_served = fleet.fleet_summary()["replicas"]
            victim = max(by_served,
                         key=lambda r: by_served[r]["served"])
            print(f"serve: killing replica {victim} after "
                  f"{served} served", file=sys.stderr)
            fleet.kill_replica(victim)
        else:
            print(f"serve: starting rolling restart after "
                  f"{served} served", file=sys.stderr)
            import threading
            # the summary reads the roll's rows from fleet._restarts
            # (fleet_summary); the return value is not needed here
            roll_thread = threading.Thread(
                target=fleet.rolling_restart, daemon=True)
            roll_thread.start()

    run_load(args, inject, inject_arg, (H, W),
             lambda img1, img2, deadline, stream, workload:
             fleet.submit(img1, img2, deadline_ms=deadline,
                          stream=stream, workload=workload),
             on_served, after_chunk=maybe_fire_event)
    if roll_thread is not None:
        roll_thread.join(timeout=600)

    summary = fleet.close()
    from raft_tpu.obs.events import sanitize_json

    def p95_ms(xs):
        return (round(1000.0 * float(np.percentile(np.asarray(xs), 95)),
                      3) if xs else None)

    summary["steady_p95_ms"] = p95_ms(lat_steady)
    summary["post_event_p95_ms"] = p95_ms(lat_after)
    if summary["steady_p95_ms"] and summary["post_event_p95_ms"]:
        summary["p95_ratio"] = round(
            summary["post_event_p95_ms"] / summary["steady_p95_ms"], 3)
    print(json.dumps({"fleet_summary": sanitize_json(summary)},
                     default=str, allow_nan=False), flush=True)

    if summary["unaccounted"]:
        print(f"serve: FLEET request conservation VIOLATED "
              f"({summary['unaccounted']} unaccounted)", file=sys.stderr)
        return 1
    if args.fail_on_slo:
        if args.slo_ms is None:
            print("serve: --fail-on-slo needs --slo_ms", file=sys.stderr)
            return 2
        p95 = summary.get("latency_p95_ms")
        if p95 is None or p95 != p95:
            print("serve: --fail-on-slo but the fleet measured no "
                  "latency (zero served requests)", file=sys.stderr)
            return 2
        if p95 > args.slo_ms:
            print(f"serve: fleet p95 {p95:.1f}ms exceeds SLO "
                  f"{args.slo_ms:.1f}ms", file=sys.stderr)
            return 1
    return 0


def main(argv=None) -> int:
    args = parse_args(argv)
    try:
        inject, inject_arg = parse_inject(args.inject)
    except ValueError as e:
        print(f"serve: {e}", file=sys.stderr)
        return 2
    if args.fleet:
        return fleet_main(args, inject, inject_arg)
    if inject in ("kill-replica", "rolling-restart"):
        print(f"serve: inject {inject} needs --fleet N", file=sys.stderr)
        return 2
    if inject == "quant-overflow" and not args.quantize:
        print("serve: inject quant-overflow needs --quantize",
              file=sys.stderr)
        return 2

    import numpy as np

    from raft_tpu.utils.platform import ensure_platform

    ensure_platform(honor_device_count_flag=False)

    import jax

    from raft_tpu.models import RAFT
    from raft_tpu.obs import RunLedger
    from raft_tpu.serve import (AOTCache, FlowServer, ServeEngine,
                                serve_config)
    from raft_tpu.serve.engine import _round8

    H, W = (_round8(x) for x in args.image_size)
    levels = tuple(int(x) for x in args.iter_levels.split(","))
    # the small model is the only sensible config for this in-process
    # synthetic driver (checkpointed full-size serving is the eval
    # CLI's job); no flag pretends otherwise
    cfg = serve_config(small=True)
    model = RAFT(cfg)

    ledger = None
    if args.ledger:
        ledger = RunLedger(args.ledger, meta={
            "entry": "serve", "image_size": [H, W],
            "batch_size": args.batch_size, "iter_levels": list(levels),
            "slo_ms": args.slo_ms,
            "backend": jax.devices()[0].platform,
            "devices": jax.device_count(),
        })

    def incident(kind, detail):
        if ledger is not None:
            ledger.incident(kind, step=0, detail=detail)

    aot = AOTCache(args.aot_cache, on_incident=incident) \
        if args.aot_cache else None

    # random-init weights: the driver exercises the serving MACHINERY;
    # checkpoint loading is the eval CLI's job (cli/evaluate.py routes
    # through the same AOTCache)
    init_img = np.zeros((1, H, W, 3), np.float32)
    variables = model.init(jax.random.PRNGKey(args.seed), init_img,
                           init_img, iters=2, train=True)

    if args.quantize:
        from raft_tpu.serve.quant import QuantServeEngine

        engine = QuantServeEngine(model, variables,
                                  batch_size=args.batch_size,
                                  aot_cache=aot, on_incident=incident)
    else:
        engine = ServeEngine(model, variables,
                             batch_size=args.batch_size, aot_cache=aot)
    if inject == "stall":
        real_forward = engine.forward

        def wedged_forward(*a, **kw):
            time.sleep(3600)           # the wedge the watchdog must kill
            return real_forward(*a, **kw)

        engine.forward = wedged_forward

    flaky = {"on": False}              # the canary-flip chaos shim
    if inject == "canary-flip":
        if not args.canary_every:
            print("serve: inject canary-flip needs --canary_every N",
                  file=sys.stderr)
            return 2
        # A flaky chip: finite-but-wrong outputs (x 1+1e-3) starting
        # AFTER warmup records the golden baseline, healed by an
        # executor recompile — exactly the corruption shape the canary's
        # recompile-and-recheck choreography must catch and recover.
        real_fwd = engine.forward
        real_invalidate = engine.invalidate

        def flaky_forward(hw, iters, img1, img2, flow_init=None):
            low, up = real_fwd(hw, iters, img1, img2,
                               flow_init=flow_init)
            if flaky["on"]:
                up = up * np.float32(1.0 + 1e-3)
            return low, up

        def healed_invalidate(*a, **kw):
            flaky["on"] = False        # the recompile replaces the
            return real_invalidate(*a, **kw)   # "corrupted" executable

        engine.forward = flaky_forward
        engine.invalidate = healed_invalidate

    qo = {"armed": False, "n": 0}      # the quant-overflow chaos shim
    if inject == "quant-overflow":
        # The Kth post-warmup batch dispatch carries pixels far outside
        # the int8 calibration premise (IMG_PREMISE_MAX): the in-graph
        # tripwire must flag it and QuantServeEngine must re-serve the
        # batch on its bf16 twin — typed degradation, zero drops.
        real_q_fwd = engine.forward

        def overflowing_forward(hw, iters, img1, img2, flow_init=None):
            if qo["armed"]:
                qo["n"] += 1
                if qo["n"] == inject_arg:
                    img1 = img1 * np.float32(1e5)
                    img2 = img2 * np.float32(1e5)
            return real_q_fwd(hw, iters, img1, img2,
                              flow_init=flow_init)

        engine.forward = overflowing_forward

    engines = {"flow": engine}
    if args.stereo_every:
        # heterogeneous session: a stereo disparity engine rides the
        # SAME queue/batcher/controller; its requests batch in their
        # own (workload, family) lane and dispatch its own executables
        engines["stereo"] = _stereo_engine_builder(
            init_img, args.seed, args.batch_size, aot)()

    tracer = None
    if ledger is not None and args.trace_sample > 0:
        from raft_tpu.obs.trace import Tracer
        tracer = Tracer(ledger, sample=args.trace_sample,
                        slo_ms=args.slo_ms)

    buckets = {"session": (H, W)}
    server = FlowServer(
        engines, buckets=buckets, queue_capacity=args.queue_capacity,
        iter_levels=levels, slo_ms=args.slo_ms,
        degrade=not args.no_degrade, warm_iters=args.warm_iters,
        ledger=ledger, watchdog_timeout_s=args.watchdog_timeout,
        continuous=args.continuous, segment_iters=args.segment_iters,
        canary_every=args.canary_every, tracer=tracer)

    t0 = time.perf_counter()
    server.warmup(warm_too=args.video_streams > 0)
    startup_s = time.perf_counter() - t0
    flaky["on"] = True                 # no-op unless inject canary-flip
    qo["armed"] = True                 # no-op unless inject quant-overflow
    stats = dict(aot.stats) if aot else {}
    print(json.dumps({"serve_startup": {
        "startup_s": round(startup_s, 3),
        "warm_hits": int(stats.get("hits", 0)),
        "cold_compiles": int(stats.get("misses", 0)),
        "cache_corrupt": int(stats.get("corrupt", 0)),
    }}), flush=True)

    served = [0]

    def on_served(latency_s):
        served[0] += 1
        if inject == "sigkill" and served[0] >= inject_arg:
            os.kill(os.getpid(), signal.SIGKILL)

    run_load(args, inject, inject_arg, (H, W),
             lambda img1, img2, deadline, stream, workload:
             server.submit(img1, img2, deadline_ms=deadline,
                           stream=stream, workload=workload),
             on_served)

    summary = server.close()
    # same strict-JSON discipline as the ledger: a zero-served run has
    # NaN percentiles, and bare NaN tokens break `| jq` on the one
    # machine-readable surface this driver promises
    from raft_tpu.obs.events import sanitize_json
    print(json.dumps({"serve_summary": sanitize_json(summary)},
                     default=str, allow_nan=False), flush=True)

    if summary["unaccounted"]:
        print(f"serve: request conservation VIOLATED "
              f"({summary['unaccounted']} unaccounted)", file=sys.stderr)
        return 1
    if args.fail_on_slo:
        if args.slo_ms is None:
            print("serve: --fail-on-slo needs --slo_ms", file=sys.stderr)
            return 2
        p95 = summary.get("latency_p95_ms")
        if p95 is None or p95 != p95:
            # no samples: a loud usage outcome, never a silent green —
            # the obs-report gate's contract, mirrored here
            print("serve: --fail-on-slo but the session measured no "
                  "latency (zero served requests)", file=sys.stderr)
            return 2
        if p95 > args.slo_ms:
            print(f"serve: p95 {p95:.1f}ms exceeds SLO "
                  f"{args.slo_ms:.1f}ms", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
