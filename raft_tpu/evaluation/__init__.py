from raft_tpu.evaluation.evaluate import (
    Evaluator,
    validate_chairs,
    validate_sintel,
    validate_kitti,
    create_sintel_submission,
    create_kitti_submission,
)

__all__ = [
    "Evaluator",
    "validate_chairs",
    "validate_sintel",
    "validate_kitti",
    "create_sintel_submission",
    "create_kitti_submission",
]
