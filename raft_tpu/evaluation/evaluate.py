"""Validation and benchmark-submission harness.

Parity targets: evaluate.py:21-166 — validate_chairs (iters=24),
validate_sintel (iters=32, centered /8 padding), validate_kitti (iters=24,
top padding, F1-all = epe>3 AND epe/mag>0.05), and the Sintel/KITTI
submission writers including the warm-start flow propagation
(evaluate.py:28-41).

Known reference quirk handled: validate_sintel averages per-frame means of
ragged arrays (evaluate.py:118-125); here EPE is the mean over all pixels
(the epe_all statistics the reference also computes), which is the
well-defined version (SURVEY.md §5).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.data import datasets, frame_utils
from raft_tpu.ops import InputPadder, forward_interpolate


class Evaluator:
    """Shape-bucketed jitted forward for eval (batch=1, test_mode).

    Eval-time inputs vary in size (KITTI especially), so the jitted forward
    is cached per padded shape; each unique shape compiles once.  The cache
    is LRU-bounded: arbitrary-folder demos with heterogeneous frame sizes
    would otherwise hold every compiled executable forever.  Evictions are
    reported on stderr so a shape-thrashing workload is visible instead of
    silently slow.

    ``spans`` (an obs.SpanRecorder) attributes each forward to the
    ``dispatch`` phase, so an eval pass driven with a recorder shows up
    in the same stall-attribution report as training — a cache-missing
    shape's compile lands inside its first dispatch span, which is
    exactly how shape thrash becomes visible in a ledger.

    ``aot_cache`` (a serve.AOTCache or a directory path) routes every
    compile through the crash-safe on-disk executable cache: repeat
    invocations of the eval/demo CLIs stop re-paying XLA compiles (the
    warm-restart story serving uses, shared here), with cold-vs-warm
    seconds logged per shape.  A torn cache entry falls back to
    recompile with a typed ``serve-cache-corrupt`` log, never a crash.
    """

    def __init__(self, model, variables, max_cached_shapes: int = 16,
                 spans=None, aot_cache=None):
        from raft_tpu.obs.spans import NULL

        self.model = model
        self.variables = variables
        self.max_cached_shapes = max_cached_shapes
        self.spans = spans if spans is not None else NULL
        if isinstance(aot_cache, str):
            from raft_tpu.serve.aot import AOTCache
            aot_cache = AOTCache(aot_cache)
        self.aot = aot_cache
        self._var_sig = None
        import collections
        self._cache = collections.OrderedDict()

    def _aot_compile(self, warm: bool, iters: int,
                     image1: np.ndarray, image2: np.ndarray, flow_init):
        """lower/compile the forward for this shape through the on-disk
        executable cache (the SAME build recipe as the serving
        executors — serve.engine.compile_test_forward); logs the
        cold-vs-warm startup cost."""
        import time

        from raft_tpu.entrypoints import (arg_signature,
                                          forward_cache_key,
                                          tree_signature)
        from raft_tpu.serve.engine import compile_test_forward

        model = self.model
        if self._var_sig is None:
            self._var_sig = tree_signature(self.variables)
        sds = lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
        args = (image1, image2) + ((flow_init,) if warm else ())
        dkey = forward_cache_key("eval_forward", model, self._var_sig,
                                 arg_signature(*args), iters, warm)

        def build():
            return compile_test_forward(
                model, self.variables, sds(image1), sds(image2), iters,
                flow_sds=sds(flow_init) if warm else None)

        t0 = time.perf_counter()
        fn, was_warm = self.aot.get_or_compile(
            dkey, build, label=f"eval_forward {image1.shape} "
                               f"iters={iters} warm={warm}")
        import logging
        logging.getLogger(__name__).info(
            "Evaluator: %s startup for shape %s iters=%d warm=%s: %.2fs",
            "warm (AOT cache)" if was_warm else "cold (compile)",
            image1.shape, iters, warm, time.perf_counter() - t0)
        return fn

    def __call__(self, image1: np.ndarray, image2: np.ndarray, iters: int,
                 flow_init: Optional[np.ndarray] = None):
        warm = flow_init is not None
        # EVERY input's shape+dtype joins the memo key: the AOT path
        # loads signature-exact compiled executables (jit would retrace
        # on a changed image2/flow_init signature; a compiled
        # executable must be keyed on the full call signature)
        from raft_tpu.entrypoints import arg_signature
        from raft_tpu.serve.engine import make_test_forward

        key = (arg_signature(*((image1, image2)
                               + ((flow_init,) if warm else ()))),
               iters, warm)
        fn = self._cache.get(key)
        if fn is None:
            if self.aot is not None:
                fn = self._aot_compile(warm, iters, image1, image2,
                                       flow_init)
            else:
                fn = make_test_forward(self.model, iters, warm=warm)
            if len(self._cache) >= self.max_cached_shapes:
                import sys
                old_key, _ = self._cache.popitem(last=False)
                # graftlint: disable=bare-print -- shape-thrash
                # diagnostic to stderr; the Evaluator takes no ledger
                print(f"Evaluator: evicting compiled shape {old_key} "
                      f"(cache limit {self.max_cached_shapes}; heterogeneous "
                      f"frame sizes recompile per shape — consider padding "
                      f"to a common size)", file=sys.stderr)
            self._cache[key] = fn
        else:
            self._cache.move_to_end(key)
        with self.spans.span("dispatch"):
            if warm:
                return fn(self.variables, image1, image2, flow_init)
            return fn(self.variables, image1, image2)


def abstract_eval_forward(iters: int = 2, hw=(64, 64),
                          overrides: Dict = None):
    """The Evaluator's jitted batch-1 test_mode forward over abstract
    inputs: the lowerable entry point behind the
    ``eval_forward``/``eval_forward_bf16`` records in
    ``raft_tpu/entrypoints.py`` (exactly the cold-path ``jax.jit`` the
    shape-bucket cache compiles, built without an Evaluator or real
    weights).

    Returns ``(fwd, (variables_sds, img1_sds, img2_sds))`` with ``fwd``
    supporting ``.lower()``.
    """
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models import RAFT

    model = RAFT(RAFTConfig(**(overrides or {})))
    H, W = hw
    img_sds = jax.ShapeDtypeStruct((1, H, W, 3), jnp.float32)
    variables_sds = jax.eval_shape(
        lambda rng, a, b: model.init(rng, a, b, iters=iters, train=True),
        jax.random.PRNGKey(0), img_sds, img_sds)
    fwd = jax.jit(lambda v, a, b: model.apply(v, a, b, iters=iters,
                                              test_mode=True))
    return fwd, (variables_sds, img_sds, img_sds)


def validate_synthetic(evaluator: Evaluator, root: str = "datasets",
                       iters: int = 24, n_samples: int = 32,
                       image_size=(368, 496)) -> Dict[str, float]:
    """EPE on held-out SyntheticShift pairs (dataset-free validation; pairs
    the `--stage synthetic` training path).  Uses a seed disjoint from the
    training stream so validation pairs are never trained on."""
    ds = datasets.SyntheticShift(image_size, length=n_samples,
                                 frames_dir=root if os.path.isdir(root) else None,
                                 seed=987654321)
    epes = []
    for i in range(len(ds)):
        s = ds[i]
        _, flow_up = evaluator(s["image1"][None], s["image2"][None], iters)
        epe = np.sqrt(((np.asarray(flow_up)[0] - s["flow"]) ** 2).sum(-1))
        epes.append(epe[s["valid"] > 0.5].reshape(-1))
    epe = float(np.concatenate(epes).mean())
    # graftlint: disable=bare-print -- reference console parity
    # (evaluate.py:92); results also reach Logger.write_dict/the ledger
    print(f"Validation Synthetic EPE: {epe:.3f}")
    return {"synthetic": epe}


def validate_chairs(evaluator: Evaluator, root: str = "datasets",
                    iters: int = 24) -> Dict[str, float]:
    """FlyingChairs validation split EPE (evaluate.py:75-92)."""
    ds = datasets.FlyingChairs(
        None, split="validation",
        root=os.path.join(root, "FlyingChairs_release/data"))
    epes = []
    for i in range(len(ds)):
        s = ds[i]
        img1 = s["image1"][None]
        img2 = s["image2"][None]
        _, flow_up = evaluator(img1, img2, iters)
        epe = np.sqrt(((np.asarray(flow_up)[0] - s["flow"]) ** 2).sum(-1))
        epes.append(epe.reshape(-1))
    epe = float(np.concatenate(epes).mean())
    # graftlint: disable=bare-print -- reference console parity
    # (evaluate.py:92); results also reach Logger.write_dict/the ledger
    print(f"Validation Chairs EPE: {epe:.3f}")
    return {"chairs": epe}


def validate_sintel(evaluator: Evaluator, root: str = "datasets",
                    iters: int = 32) -> Dict[str, float]:
    """Sintel-train clean+final EPE (evaluate.py:95-127)."""
    results = {}
    for dstype in ["clean", "final"]:
        ds = datasets.MpiSintel(None, split="training", dstype=dstype,
                                root=os.path.join(root, "Sintel"))
        epes = []
        for i in range(len(ds)):
            s = ds[i]
            padder = InputPadder(s["image1"][None].shape)
            im1, im2 = padder.pad(jnp.asarray(s["image1"][None]),
                                  jnp.asarray(s["image2"][None]))
            _, flow_up = evaluator(np.asarray(im1), np.asarray(im2), iters)
            flow = np.asarray(padder.unpad(flow_up))[0]
            epe = np.sqrt(((flow - s["flow"]) ** 2).sum(-1))
            epes.append(epe.reshape(-1))
        epe_all = np.concatenate(epes)
        results[dstype] = float(epe_all.mean())
        # graftlint: disable=bare-print -- reference console parity
        # (evaluate.py:126); results also reach Logger.write_dict
        print(f"Validation ({dstype}) EPE: {results[dstype]:.3f}, "
              f"1px: {(epe_all < 1).mean():.3f}, "
              f"3px: {(epe_all < 3).mean():.3f}, "
              f"5px: {(epe_all < 5).mean():.3f}")
    return results


def validate_kitti(evaluator: Evaluator, root: str = "datasets",
                   iters: int = 24) -> Dict[str, float]:
    """KITTI-15 train EPE + F1-all (evaluate.py:130-166)."""
    ds = datasets.KITTI(None, split="training",
                        root=os.path.join(root, "KITTI"))
    epe_list, out_list = [], []
    for i in range(len(ds)):
        s = ds[i]
        padder = InputPadder(s["image1"][None].shape, mode="kitti")
        im1, im2 = padder.pad(jnp.asarray(s["image1"][None]),
                              jnp.asarray(s["image2"][None]))
        _, flow_up = evaluator(np.asarray(im1), np.asarray(im2), iters)
        flow = np.asarray(padder.unpad(flow_up))[0]

        epe = np.sqrt(((flow - s["flow"]) ** 2).sum(-1))
        mag = np.sqrt((s["flow"] ** 2).sum(-1))
        valid = s["valid"] >= 0.5
        out = ((epe > 3.0) & ((epe / np.maximum(mag, 1e-12)) > 0.05))
        epe_list.append(epe[valid].mean())
        out_list.append(out[valid])

    epe = float(np.mean(epe_list))
    f1 = 100.0 * float(np.concatenate(out_list).mean())
    # graftlint: disable=bare-print -- reference console parity
    # (evaluate.py:165); results also reach Logger.write_dict
    print(f"Validation KITTI: EPE {epe:.3f}, F1-all {f1:.2f}")
    return {"kitti-epe": epe, "kitti-f1": f1}


def create_sintel_submission(evaluator: Evaluator, root: str = "datasets",
                             iters: int = 32, warm_start: bool = False,
                             output_path: str = "sintel_submission") -> None:
    """Write Sintel test-split .flo files; optional warm start carries the
    low-res flow forward through each scene (evaluate.py:21-50)."""
    for dstype in ["clean", "final"]:
        ds = datasets.MpiSintel(None, split="test", dstype=dstype,
                                root=os.path.join(root, "Sintel"))
        flow_prev, sequence_prev = None, None
        for i in range(len(ds)):
            s = ds[i]
            sequence, frame = s["extra_info"]
            if sequence != sequence_prev:
                flow_prev = None

            padder = InputPadder(s["image1"][None].shape)
            im1, im2 = padder.pad(jnp.asarray(s["image1"][None]),
                                  jnp.asarray(s["image2"][None]))
            flow_low, flow_up = evaluator(np.asarray(im1), np.asarray(im2),
                                          iters, flow_init=flow_prev)
            flow = np.asarray(padder.unpad(flow_up))[0]

            if warm_start:
                flow_prev = forward_interpolate(np.asarray(flow_low)[0])[None]

            out_dir = os.path.join(output_path, dstype, sequence)
            os.makedirs(out_dir, exist_ok=True)
            frame_utils.write_flow(
                os.path.join(out_dir, f"frame{frame + 1:04d}.flo"), flow)
            sequence_prev = sequence


def create_kitti_submission(evaluator: Evaluator, root: str = "datasets",
                            iters: int = 24,
                            output_path: str = "kitti_submission") -> None:
    """Write KITTI test-split 16-bit PNGs (evaluate.py:53-71)."""
    ds = datasets.KITTI(None, split="testing",
                        root=os.path.join(root, "KITTI"))
    os.makedirs(output_path, exist_ok=True)
    for i in range(len(ds)):
        s = ds[i]
        (frame_id,) = s["extra_info"]
        padder = InputPadder(s["image1"][None].shape, mode="kitti")
        im1, im2 = padder.pad(jnp.asarray(s["image1"][None]),
                              jnp.asarray(s["image2"][None]))
        _, flow_up = evaluator(np.asarray(im1), np.asarray(im2), iters)
        flow = np.asarray(padder.unpad(flow_up))[0]
        frame_utils.write_flow_kitti(os.path.join(output_path, frame_id),
                                     flow)
