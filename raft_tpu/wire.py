"""Compact wire encodings for host->device batch transfer.

Images already ship as uint8 (FlowDataset._pack).  This module adds the
same treatment for the supervision tensors: flow as int16 fixed-point at
1/64 px — exactly the quantization KITTI ground truth already has on
disk (the u16 `(v - 2**15) / 64` encoding, reference
core/utils/frame_utils.py:116-120) — and the valid mask as uint8.  A
chairs-config batch (8 x 368x496: 6 uint8 image bytes/px either way)
drops from ~26.3 MB (f32 flow+valid: +12 bytes/px) to ~16.1 MB (+5
bytes/px) — a 39% cut on any host->device link the loader has to cross
(PCIe on a TPU VM, the tunnel in this environment).

Saturation is safe by construction: int16/64 covers +-511.98 px, and the
training loss masks |flow| > MAX_FLOW = 400 (reference train.py:42,54-55)
— a saturated value still exceeds the mask threshold, so the valid
semantics survive encoding for every representable and unrepresentable
flow alike.  (The dense |flow| < 1000 validity rule runs on the f32 flow
BEFORE encoding, datasets._pack.)

Decode happens on device as the train step's first op (training/step.py
decode_flow/decode_valid below work on numpy and jax arrays alike);
quantization error is at most 1/128 px, far below label noise.
"""

from __future__ import annotations

import numpy as np

# 1/64 px — KITTI's native ground-truth quantization
# (frame_utils.py:116-120).
FLOW_WIRE_SCALE = 64.0
_I16_MAX = 32767
# Largest representable flow magnitude on the int16 wire (+-511.98 px);
# the train step refuses the packed wire when max_flow exceeds this
# (training/step.py), keeping the saturation<->loss-mask invariant.
WIRE_FLOW_MAX = _I16_MAX / FLOW_WIRE_SCALE

WIRE_FORMATS = ("f32", "int16")


def check_wire_format(wire_format: str) -> str:
    """Validate a wire-format name (the single owner of the whitelist)."""
    if wire_format not in WIRE_FORMATS:
        raise ValueError(
            f"wire_format must be one of {WIRE_FORMATS}, "
            f"got {wire_format!r}")
    return wire_format


def encode_flow_i16(flow: np.ndarray) -> np.ndarray:
    """f32 flow -> int16 fixed point at 1/64 px, saturating at +-511.98."""
    q = np.rint(np.asarray(flow, np.float32) * FLOW_WIRE_SCALE)
    return np.clip(q, -_I16_MAX, _I16_MAX).astype(np.int16)


def decode_flow(flow):
    """Inverse of encode_flow_i16; passes f32 through untouched.

    Works on numpy and jax arrays (only dtype/astype/mul are used), so
    the same helper serves the device-side train step and host-side
    tests.
    """
    if flow.dtype == np.int16:
        return flow.astype(np.float32) * np.float32(1.0 / FLOW_WIRE_SCALE)
    return flow


def decode_valid(valid):
    """uint8 (or bool) wire mask -> f32; passes f32 through untouched."""
    if valid.dtype != np.float32:
        return valid.astype(np.float32)
    return valid
