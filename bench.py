"""Benchmark runner: FlyingChairs-config training throughput on one chip.

Prints ONE JSON line:
  {"metric": "image-pairs/sec/chip", "value": N, "unit": "pairs/s",
   "vs_baseline": N, "mfu": N, "fed_pairs_per_s": N}

Measured config mirrors the reference's mixed-precision chairs recipe
(train_mixed.sh:3: batch 8, crop 368x496, 12 refinement iterations,
bf16 compute) — the primary metric named in BASELINE.json.

- ``value``: device-rate pairs/s, synthetic resident batch (pure step time).
- ``mfu``: model FLOPs utilization — XLA's analyzed FLOPs per step divided
  by (step time x chip peak bf16 FLOP/s).
- ``fed_pairs_per_s``: same step fed by the real host pipeline, on the
  lane the train CLI's auto policy would run (``fed_lane``) — with an
  accelerator attached, DEVICE-SIDE augmentation (SyntheticShift raw
  frames + aug params -> DataLoader -> prefetch_to_device ->
  data/device_aug.py jitted graph: the host only generates frames and
  samples parameters; photometric/eraser/resize/flip/crop run on-chip
  inside the h2d lane).  Both lanes are always reported:
  ``fed_pairs_per_s_device`` and ``fed_pairs_per_s_host`` (the
  numpy/cv2 parity fallback).  Interpret against ``host_cores``:
  generation + dense augmentation cost ~27 ms of CPU per sample, which
  capped the round-5 fed rate at 11.2 pairs/s on this 1-core tunnel
  host against a 34 pairs/s device rate — the ~3x input-bound gap the
  device-aug lane exists to close (the loader alone sustains 37
  samples/s with host aug and 111/s without, scripts/data_bench.py).

Baseline: the reference repo publishes no numbers (BASELINE.md).  The
denominator used here is 7.0 pairs/s — an A100 estimate derived from the
RAFT paper's training-time claim (chairs 100k steps, batch 10, ~10 h on
two 2080 Ti => ~2.8 pairs/s/GPU, scaled by the ~2.5x A100/2080Ti training
speedup).  vs_baseline = measured / 7.0, so 2.0 meets the north-star
"2x A100 pairs/sec/chip" target.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

from raft_tpu.resilience.exit_codes import ExitCode

A100_BASELINE_PAIRS_PER_S = 7.0

# Dense bf16 peak FLOP/s by TPU generation (device_kind substrings,
# checked in order).  Used for the MFU line only.
_PEAK_BF16 = [
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v6 lite", 918e12), ("v6e", 918e12),
    ("v5p", 459e12), ("v5", 459e12),
    ("v4", 275e12),
]


def _fail(reason: str, backend_down: bool = True) -> None:
    """The driver records this script's stdout as the round's scoreboard;
    protect it — one parseable line with a diagnosis, not a traceback.
    ``backend_down=False`` drops the tunnel-recovery suffix (config-misuse
    errors aren't fixed by recovering hardware)."""
    suffix = (" — recover the TPU tunnel, then run "
              "scripts/tpu_validation.py" if backend_down else "")
    print(json.dumps({
        "metric": "image-pairs/sec/chip", "value": 0.0, "unit": "pairs/s",
        "vs_baseline": 0.0,
        "error": reason + suffix,
    }))
    sys.exit(ExitCode.FATAL)


def preflight(timeout_s: int = 150) -> str:
    """Probe backend init in a subprocess so a hung tunnel cannot wedge the
    bench itself (round-1 failure mode: BENCH_r01 died 40 frames deep in
    device_put when the axon backend was down).  Also rejects a silent CPU
    fallback — a CPU run of the chairs config takes minutes per step and
    would poison the scoreboard; set RAFT_BENCH_ALLOW_CPU=1 to bench on
    CPU deliberately.  Returns the probed platform name.

    Patient retry (round-2 verdict item 1a): the tunnel wedges and
    recovers on minute scales, so a scoreboard artifact should not give
    up after one probe window.  Re-probes every ~2.5 min until
    RAFT_BENCH_RETRY_MINUTES (default 25) has elapsed; set it to 0 to
    restore single-shot behavior."""
    retry_min = float(os.environ.get("RAFT_BENCH_RETRY_MINUTES", "25"))
    deadline = time.monotonic() + retry_min * 60
    # ensure_platform: an explicit JAX_PLATFORMS=cpu must actually take
    # effect in the probe (the env var alone does not beat the image's
    # pinned axon plugin — utils/platform.py)
    code = ("from raft_tpu.utils.platform import ensure_platform; "
            "ensure_platform(honor_device_count_flag=False); "
            "import jax; d = jax.devices()[0]; "
            "print(d.platform, '|', d.device_kind)")
    last = ""
    attempt = 0
    while True:
        if attempt:
            if time.monotonic() >= deadline:
                break
            print(f"bench preflight: backend not up ({last}); retrying "
                  f"(attempt {attempt + 1})", file=sys.stderr)
            time.sleep(150)
        attempt += 1
        try:
            # cwd pinned to the repo root: the probe imports raft_tpu,
            # which is not pip-installed
            proc = subprocess.run([sys.executable, "-c", code],
                                  capture_output=True, text=True,
                                  timeout=timeout_s,
                                  cwd=os.path.dirname(
                                      os.path.abspath(__file__)))
        except subprocess.TimeoutExpired:
            last = f"backend init timed out after {timeout_s}s"
            continue
        if proc.returncode == 0:
            platform = proc.stdout.split("|")[0].strip()
            if (platform == "cpu"
                    and os.environ.get("RAFT_BENCH_ALLOW_CPU", "") in
                    ("", "0")):
                _fail("backend fell back to CPU (expected the tunneled "
                      "TPU; set RAFT_BENCH_ALLOW_CPU=1 to bench on CPU "
                      "anyway)")
            return platform
        tail = (proc.stderr or "").strip().splitlines()
        last = tail[-1][:300] if tail else f"rc={proc.returncode}"
    _fail(f"backend unavailable ({last})")


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for sub, peak in _PEAK_BF16:
        if sub in kind:
            return peak
    return 0.0


def pod_scaling_stamp(repo: str = None):
    """The pod-scaling stamp: per-device-count throughput + scaling
    efficiency of the ZeRO-sharded step, lifted from the newest
    MULTICHIP_r*.json dryrun artifact (its tail carries the
    machine-parseable ``MULTICHIP_SCALING`` line __graft_entry__.py
    prints).  Bench itself owns ONE chip, so it cites the driver
    dryrun's 1->n virtual-mesh curve rather than re-running an
    8-device sweep inside the bench budget; ``source`` names the
    artifact so a stale stamp is auditable.  None when no dryrun
    artifact (or no scaling line) exists — the scoreboard key is
    simply absent on a fresh checkout."""
    import glob

    repo = repo or os.path.dirname(os.path.abspath(__file__))
    for path in sorted(glob.glob(os.path.join(repo, "MULTICHIP_r*.json")),
                       reverse=True):
        try:
            with open(path) as f:
                tail = json.load(f).get("tail", "")
        except (OSError, ValueError) as e:
            print(f"pod_scaling_stamp: skipping unreadable {path}: {e}",
                  file=sys.stderr)
            continue
        if not isinstance(tail, str):
            continue
        for line in tail.splitlines():
            if not line.startswith("MULTICHIP_SCALING "):
                continue
            try:
                rec = json.loads(line.split(" ", 1)[1])
            except ValueError as e:
                print(f"pod_scaling_stamp: malformed scaling line in "
                      f"{path}: {e}", file=sys.stderr)
                continue
            return {"source": os.path.basename(path),
                    "layout": rec.get("layout"),
                    "weak_scaling": rec.get("weak_scaling"),
                    "devices": rec.get("devices", {})}
    return None


def _make_fed_loader(B, H, W, seed: int = 1, device_aug: bool = False):
    """Host pipeline for the fed benchmark: procedural image pairs run
    through the real dense augmentor (jitter/scale/crop — the chairs
    recipe's host-side cost), batched and prefetched by the real loader.

    ``device_aug=True`` is the split pipeline (raft_tpu/data/device_aug):
    the host only generates frames and samples aug params; the dense
    augmentation runs as a jitted batch on the accelerator, fused into
    the h2d lane.  Returns ``(loader, device_fn)`` — device_fn is None
    on the host-augmented path."""
    from raft_tpu.data.datasets import SyntheticShift
    from raft_tpu.data.device_aug import make_device_augment
    from raft_tpu.data.loader import DataLoader

    ds = SyntheticShift(
        image_size=(H + 32, W + 32), length=512, seed=seed,
        aug_params=dict(crop_size=(H, W), min_scale=0.0, max_scale=0.2,
                        do_flip=True),
        wire_format="int16")
    device_fn = None
    if device_aug:
        ds.enable_device_aug()
        device_fn = make_device_augment((H, W), sparse=False,
                                        wire_format="int16")
    # Workers capped at the core count (loader.default_num_workers): on
    # the 1-core tunnel host, 4 threads time-slicing one core add
    # GIL/scheduler thrash on top of the ~27 ms/sample augment cost —
    # the source of the round-4 fed lane's 2x run-to-run spread
    # (6.5-10.8 pairs/s); a worker per core is the stable configuration,
    # and real TPU-VM hosts have >= 4.
    return DataLoader(ds, batch_size=B, num_workers=None,
                      drop_last=True, seed=seed, prefetch=3), device_fn


def main():
    platform = preflight()

    from raft_tpu.utils.platform import ensure_platform

    ensure_platform(honor_device_count_flag=False)

    import jax
    import jax.numpy as jnp

    from raft_tpu.config import RAFTConfig
    from raft_tpu.models import RAFT
    from raft_tpu.training import create_train_state, make_optimizer
    from raft_tpu.training.step import make_train_step

    import dataclasses

    from raft_tpu.config import STAGE_PRESETS

    # The measured config IS the chairs_mixed stage preset (reference's
    # train_mixed.sh recipe), so bench and training can't drift apart;
    # scripts/perf_probe.py derives its variants from the same source.
    preset = STAGE_PRESETS["chairs_mixed"]
    B = preset.data.batch_size
    H, W = preset.data.image_size
    iters = preset.train.iters

    # RAFT_BENCH_TINY=1: shrink everything so the full bench path (incl.
    # MFU line and fed lane) smoke-runs on CPU in tests — combine with
    # RAFT_BENCH_ALLOW_CPU=1.  Numbers produced this way are meaningless,
    # so tiny mode is CPU-only (a stale env var must not let a shrunk run
    # masquerade as the real chairs-config scoreboard number) and the
    # output line carries "tiny": true.
    tiny = os.environ.get("RAFT_BENCH_TINY", "") not in ("", "0")
    if tiny and platform != "cpu":
        _fail("RAFT_BENCH_TINY is set but the backend is "
              f"'{platform}' — tiny mode is for CPU smoke tests only; "
              "unset it for a real benchmark run", backend_down=False)
    if tiny:
        B, H, W, iters = 1, 64, 64, 2

    rng = np.random.default_rng(0)
    # The batch carries the wire dtypes the host pipeline ships — uint8
    # images and, since round 5, int16 fixed-point flow + uint8 valid
    # (raft_tpu/wire.py: ~16.1 MB/batch instead of ~26.3; the tunnel-bound
    # fed lane is bytes-limited) — so the ONE compiled executable serves
    # both the device lane and the fed lane (a dtype mismatch would make
    # the fed lane silently recompile or fail against the lowered
    # executable).  NOTE: this breaks fed-lane comparability with the
    # pre-wire r05_bench_{a,b} artifacts (those shipped the f32 wire).
    from raft_tpu.wire import encode_flow_i16
    batch = {
        "image1": jnp.asarray(rng.uniform(0, 255, (B, H, W, 3)).astype(np.uint8)),
        "image2": jnp.asarray(rng.uniform(0, 255, (B, H, W, 3)).astype(np.uint8)),
        "flow": jnp.asarray(encode_flow_i16(
            (rng.standard_normal((B, H, W, 2)) * 5).astype(np.float32))),
        "valid": jnp.ones((B, H, W), np.uint8),
    }

    # remat=True (from the preset): without it the unrolled 12-iteration
    # scan needs ~21 GB of HBM at this resolution (v5e has 15.75 GB).
    # dots_saveable keeps matmul outputs and recomputes only elementwise
    # work: 16.0 pairs/s vs 14.2 for full recompute on v5e.
    # corr_dtype=bfloat16 halves the volume traffic and runs the lookup
    # matmuls at full MXU rate (f32 accumulation; ~0.5% relative error).
    cfg = dataclasses.replace(preset.model, corr_dtype="bfloat16")
    deferred = cfg.deferred_corr_grad
    # Fused Pallas update block (ops/gru_pallas.py): the benched value
    # follows the config's auto policy (models/update.py
    # resolve_fused_update_block); the A/B sub-lane below measures the
    # OTHER side so the scoreboard always carries both.  The serve lane
    # shares this cfg, so requests_per_s_per_chip runs against the
    # fused forward graph whenever the headline does.
    from raft_tpu.models.update import resolve_fused_update_block
    fused = resolve_fused_update_block(cfg)

    def build(cfg):
        model = RAFT(cfg)
        tx, _ = make_optimizer(lr=4e-4, num_steps=1000, wdecay=1e-4)
        state = create_train_state(model, tx, jax.random.PRNGKey(0), batch,
                                   iters=iters)
        step = make_train_step(model, iters=iters, gamma=0.8,
                               max_flow=400.0, donate=True)
        # Compile once via lower/compile: the same executable serves the
        # timing loop AND exposes XLA's FLOPs estimate for the MFU line.
        # scoped_vmem 32 MiB: the round-5 compiler-flag scan measured the
        # chairs step at 228-229 ms vs 241-243 at the 64 MiB default
        # (~+5.8%; 24-32 MiB is a plateau, 48+ and 16 both lose —
        # docs/tpu_runs/r05_probe_vmem.txt).  Overridable for other
        # configs; only applies to this einsum-path executable — Pallas
        # lookup configs budget their own VMEM and should leave the
        # default (scripts/perf_probe.py xla_vmem* variants re-measure).
        vmem_kib = os.environ.get("RAFT_SCOPED_VMEM_KIB", "32768")
        if vmem_kib and not vmem_kib.isdigit():
            _fail(f"RAFT_SCOPED_VMEM_KIB={vmem_kib!r} is not an integer "
                  f"KiB count (e.g. 32768; 0 disables the override)",
                  backend_down=False)
        copts = ({"xla_tpu_scoped_vmem_limit_kib": vmem_kib}
                 if platform == "tpu" and vmem_kib not in ("", "0")
                 else None)
        flops = 0.0
        try:
            lowered = step.lower(state, batch)
            try:
                compiled = lowered.compile(compiler_options=copts)
            except Exception as ce:
                if copts is None:
                    raise
                # vmem override rejected (older jax / other backend):
                # keep the MFU line, lose only the tuning — and SAY so,
                # or the scoreboard number gets attributed to a tuning
                # that never applied (the _is_oom comment's silent-
                # downgrade rule)
                print(f"bench: scoped-vmem override {vmem_kib} KiB "
                      f"rejected ({type(ce).__name__}: {str(ce)[:120]}); "
                      f"compiled with backend defaults", file=sys.stderr)
                compiled = lowered.compile()
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            flops = float((ca or {}).get("flops", 0.0))
            step = compiled
        except Exception as e:
            # plain jitted step; mfu reported as 0
            print(f"bench: AOT cost analysis unavailable "
                  f"({type(e).__name__}: {str(e)[:120]}); continuing with "
                  f"the plain jitted step", file=sys.stderr)
        # Warmup / compile.  Synchronization must be a host copy: over the
        # axon tunnel, block_until_ready returns before execution
        # finishes, which silently times dispatch instead of compute.
        state, metrics = step(state, batch)
        float(metrics["loss"])
        return step, state, flops

    def _is_oom(e) -> bool:
        # Only genuine resource exhaustion triggers the fallback; compile
        # or trace bugs in the default path must fail loudly (a silent
        # config downgrade would mask them — round-2 advisor finding).
        return ("RESOURCE_EXHAUSTED" in str(e)
                or "Out of memory" in str(e) or "out of memory" in str(e))

    def _is_lowering(e) -> bool:
        # A Pallas/Mosaic lowering failure: the fused-kernel configs can
        # regress at the KERNEL-COMPILER layer (new jaxlib, new shape)
        # where the einsum/flax path still compiles fine.
        s = str(e)
        return any(t in s for t in ("Mosaic", "mosaic", "Pallas",
                                    "pallas", "infer-vector-layout",
                                    "Unsupported shape cast"))

    # Degradation ladder: a failed compile retries with the responsible
    # knob off instead of killing the lane, and every fallback that
    # fired is stamped into the JSON line — a Pallas lowering regression
    # degrades to a MEASURED reference run, visibly, not a dead bench.
    fallbacks = []
    while True:
        try:
            step, state, flops_per_step = build(cfg)
            break
        except Exception as e:
            if fused and (_is_lowering(e) or _is_oom(e)):
                print(f"bench: fused-update-block config failed to "
                      f"build ({str(e)[:200]}); retrying with "
                      f"fused_update_block=False", file=sys.stderr)
                fused = False
                cfg = dataclasses.replace(cfg, fused_update_block=False)
                fallbacks.append("fused_update_block=False")
                continue
            if deferred and _is_oom(e):
                # the deferred-grad path's stacked d_win buffer is the
                # config's dominant backward transient
                print(f"bench: default config exhausted memory "
                      f"({str(e)[:200]}); retrying with "
                      f"deferred_corr_grad=False", file=sys.stderr)
                deferred = False
                cfg = dataclasses.replace(cfg, deferred_corr_grad=False)
                fallbacks.append("deferred_corr_grad=False")
                continue
            # Nothing left to degrade — propagate so _fail protects the
            # scoreboard rather than silently mis-attributing a number.
            raise

    # Telemetry: spans + optional run ledger (RAFT_BENCH_LEDGER=path).
    # The ledger is written OUTSIDE the bulk timing loop, so the headline
    # number is untouched; render it with python -m raft_tpu.obs report.
    from raft_tpu.obs import HealthMonitor, RunLedger, SpanRecorder
    from raft_tpu.obs.spans import NULL as NULL_SPANS
    from raft_tpu.training.profiler import StepTimer

    ledger = None
    spans = NULL_SPANS
    ledger_path = os.environ.get("RAFT_BENCH_LEDGER", "")
    if ledger_path:
        ledger = RunLedger(ledger_path, meta={
            "entry": "bench", "batch_size": B, "image_size": [H, W],
            "iters": iters, "backend": platform,
            "devices": jax.device_count(),
        })
    health = HealthMonitor(ledger=ledger)

    n_steps = 2 if tiny else 10
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step(state, batch)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    pairs_per_s = B * n_steps / dt
    peak = _peak_flops(jax.devices()[0])
    mfu = (flops_per_step * n_steps / dt / peak) if peak else 0.0

    # Percentile lane: per-step-synced timing so the tail (recompiles,
    # host stalls) is visible — mean-only throughput hides it.  Separate
    # from the bulk loop above because the per-step sync serializes the
    # dispatch pipeline: `value` stays the pipelined device rate.
    # The span recorder is created HERE so its first window opens after
    # the uninstrumented bulk loop — anchoring it earlier would dump the
    # whole bulk loop into the report's 'other' bucket.
    if ledger is not None:
        spans = SpanRecorder(ledger=ledger)
    timer = StepTimer(warmup=1)
    timer.tick()
    for _ in range(4 if tiny else 12):
        with spans.span("dispatch"):
            state, metrics = step(state, batch)
        with spans.span("block"):
            timer.tick(metrics)
        spans.step_boundary()
    step_pct = timer.summary()
    health.sample_memory(n_steps)
    spans.flush(n_steps)

    # SDC digest-cadence overhead (resilience/sdc.py): the per-cadence
    # cost of --sdc_vote_every is one capture (device_get of the
    # pre-step state) + one param-tree digest + one replayed step +
    # one host compare — measured here against the steady-state p50 at
    # the acceptance cadence of 100, stamped into the JSON line.  The
    # always-on in-graph grad digest is already inside `value` itself.
    def _sdc_overhead():
        nonlocal state
        from raft_tpu.resilience.sdc import (float_bits_hex,
                                             param_tree_digest)

        t0 = time.perf_counter()
        host_state = jax.device_get(state)
        param_tree_digest(host_state.params)
        state, m = step(state, batch)     # the replayed-step cost
        float_bits_hex(float(m["grad_digest"]))
        per_cadence_s = time.perf_counter() - t0
        cadence = 100
        pct = 100.0 * per_cadence_s / max(cadence * step_pct["p50"], 1e-9)
        return {"sdc_vote_every": cadence,
                "sdc_vote_overhead_pct": round(pct, 3)}

    sdc_metrics = {}
    try:
        sdc_metrics = _sdc_overhead()
    except Exception as e:  # the overhead lane must never sink the bench
        print(f"sdc overhead bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)

    # Fed variants: identical step, batches produced by the real host
    # pipeline.  Two lanes, so the device-aug win is measured rather
    # than asserted: ``device`` ships raw frames + aug params and runs
    # the dense augmentation on-chip (data/device_aug.py — the default
    # production path); ``host`` runs the numpy/cv2 augmentor (the
    # parity fallback, ~27 ms of host CPU per sample).
    def _fed_lane(device_aug: bool):
        nonlocal state, metrics
        loader, device_fn = _make_fed_loader(B, H, W, device_aug=device_aug)
        from raft_tpu.data.loader import prefetch_to_device
        it = prefetch_to_device(iter(loader), size=2, device_fn=device_fn)
        try:
            fed0 = next(it)  # warm the pipeline (+ any reshape recompile)
            state, metrics = step(state, fed0)
            float(metrics["loss"])
            # 30 timed fed steps (vs 10 for the device lane): the fed
            # number is host-bound on this 1-core tunnel host; a longer
            # window plus the worker-per-core loader cap bounds the
            # run-to-run spread that round 4 measured at 2x
            n_fed = 2 if tiny else 30
            t0 = time.perf_counter()
            for _ in range(n_fed):
                with spans.span("data"):
                    fed_batch = next(it)
                with spans.span("dispatch"):
                    state, metrics = step(state, fed_batch)
                spans.step_boundary()
            float(metrics["loss"])
            rate = B * n_fed / (time.perf_counter() - t0)
            spans.flush(n_fed)
        finally:
            # join the loader's worker pool even when this lane dies:
            # an abandoned pool would compete with the NEXT lane's
            # timing for the single host core, and an abandoned
            # generator tears down its executor at interpreter exit,
            # after threading internals are gone
            it.close()
        return rate

    fed_dev = 0.0                # device-aug path
    fed_pairs_per_s_host = 0.0   # host-aug parity fallback
    try:
        fed_dev = _fed_lane(device_aug=True)
    except Exception as e:  # the fed lane must never sink the scoreboard
        print(f"fed bench (device aug) failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        fed_pairs_per_s_host = _fed_lane(device_aug=False)
    except Exception as e:
        print(f"fed bench (host aug) failed: {type(e).__name__}: {e}",
              file=sys.stderr)

    # Serving lane (raft_tpu/serve): synthetic requests through the
    # real FlowServer (queue -> batcher -> AOT executor) at the bench
    # resolution with the bench model's weights — requests/s/chip and
    # the p95 request latency become scoreboard lanes next to the
    # training numbers.  Full-quality iterations only (no degradation
    # ladder: the lane measures capacity, not the shed behavior).
    def _serve_lane():
        import tempfile

        from raft_tpu.obs.events import RunLedger
        from raft_tpu.obs.trace import DEFAULT_SAMPLE, Tracer
        from raft_tpu.serve.engine import ServeEngine
        from raft_tpu.serve.server import FlowServer

        serve_vars = {"params": state.params}
        bs = getattr(state, "batch_stats", None)
        if bs:
            serve_vars["batch_stats"] = bs
        serve_b = min(2, B)
        # ONE engine for both A/B halves: the executables compile once,
        # so the traced half re-measures only the request path
        engine = ServeEngine(RAFT(cfg), serve_vars, batch_size=serve_b)
        n_req = 4 if tiny else 24

        def run_load(tracer):
            server = FlowServer(engine, buckets={"bench": (H, W)},
                                queue_capacity=max(8, 4 * serve_b),
                                iter_levels=(iters,), degrade=False,
                                tracer=tracer)
            try:
                server.warmup(warm_too=False)
                rng_s = np.random.default_rng(7)

                def frame():
                    return rng_s.uniform(0, 255,
                                         (H, W, 3)).astype(np.float32)

                t0 = time.perf_counter()
                done = []
                for i in range(n_req):
                    done.append(server.submit(frame(), frame()))
                    if (i + 1) % serve_b == 0:
                        for f in done[-serve_b:]:
                            f.result(timeout=600)
                for f in done:
                    f.result(timeout=600)
                wall = time.perf_counter() - t0
                summary = server.close()
                server = None
                return wall, summary
            finally:
                if server is not None:
                    server.close()

        # tracing-off half FIRST (it also pays any residual engine
        # warm-in), then the traced half at the DEFAULT head-sampling
        # rate against a real ledger — the A/B the <= 2 % per-request
        # tracing overhead budget is measured by
        wall_off, summary = run_load(None)
        td = tempfile.mkdtemp(prefix="bench_trace_")
        trace_ledger = RunLedger(os.path.join(td, "events.jsonl"),
                                 meta={"entry": "bench-trace-ab"})
        wall_traced, _ = run_load(Tracer(trace_ledger,
                                         sample=DEFAULT_SAMPLE))
        trace_ledger.close()
        overhead_pct = round(100.0 * (wall_traced - wall_off)
                             / max(wall_off, 1e-9), 2)
        return {
            "requests_per_s_per_chip": round(n_req / wall_off, 3),
            "latency_p95_ms": summary.get("latency_p95_ms", 0.0),
            "trace_overhead_pct": overhead_pct,
            "trace_sample": DEFAULT_SAMPLE,
            # <= 2 is the budget; wall-clock noise on a small lane can
            # swing either way, so the verdict is published, not gated
            "trace_overhead_ok": bool(overhead_pct <= 2.0),
        }

    serve_metrics = {"requests_per_s_per_chip": 0.0,
                     "latency_p95_ms": 0.0,
                     "trace_overhead_pct": 0.0,
                     "trace_sample": 0,
                     "trace_overhead_ok": True}
    try:
        serve_metrics = _serve_lane()
    except Exception as e:  # the serve lane must never sink the scoreboard
        print(f"serve bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)

    # Int8 serving lane (raft_tpu/serve/quant.py, the graph graftlint
    # engine 7 certifies): the same synthetic request load through a
    # QuantServeEngine — q8 requests/s and p95 land NEXT TO the bf16
    # serving lane so the quantization win (or regression) is a
    # scoreboard delta, not an assertion.  ``q8_epe_delta`` is the
    # quality price: mean EPE between the q8 and bf16 twins' upsampled
    # flow on one identical batch (the 12-vs-32-iter harness in
    # tests/test_quant.py gates the same delta against a budget; here
    # it is measured and published every round).  ``q8_fallbacks``
    # must stay 0 on this in-range load — a nonzero count means the
    # calibrated envelope no longer covers ordinary pixels.
    def _q8_serve_lane():
        from raft_tpu.serve.quant import QuantServeEngine
        from raft_tpu.serve.server import FlowServer

        serve_vars = {"params": state.params}
        bs = getattr(state, "batch_stats", None)
        if bs:
            serve_vars["batch_stats"] = bs
        serve_b = min(2, B)
        engine = QuantServeEngine(RAFT(cfg), serve_vars,
                                  batch_size=serve_b)
        server = FlowServer(engine, buckets={"bench": (H, W)},
                            queue_capacity=max(8, 4 * serve_b),
                            iter_levels=(iters,), degrade=False)
        try:
            server.warmup(warm_too=False)
            rng_q = np.random.default_rng(7)  # the bf16 lane's load

            def frame():
                return rng_q.uniform(0, 255, (H, W, 3)).astype(np.float32)

            n_req = 4 if tiny else 24
            t0 = time.perf_counter()
            done = []
            for i in range(n_req):
                done.append(server.submit(frame(), frame()))
                if (i + 1) % serve_b == 0:
                    for f in done[-serve_b:]:
                        f.result(timeout=600)
            for f in done:
                f.result(timeout=600)
            wall = time.perf_counter() - t0
            summary = server.close()
            server = None
            # quality delta: one identical batch through both twins the
            # engine holds (executables already warm from the load)
            img1 = np.stack([frame() for _ in range(serve_b)])
            img2 = np.stack([frame() for _ in range(serve_b)])
            _, up_q = engine.forward((H, W), iters, img1, img2)
            _, up_f = engine.fallback.forward((H, W), iters, img1, img2)
            epe_delta = float(np.mean(np.linalg.norm(
                np.asarray(up_q, np.float32)
                - np.asarray(up_f, np.float32), axis=-1)))
            return {
                "q8_requests_per_s_per_chip": round(n_req / wall, 3),
                "q8_latency_p95_ms": summary.get("latency_p95_ms", 0.0),
                "q8_epe_delta": round(epe_delta, 4),
                "q8_fallbacks": engine.fallbacks,
            }
        finally:
            if server is not None:
                server.close()

    q8_metrics = {"q8_requests_per_s_per_chip": 0.0,
                  "q8_latency_p95_ms": 0.0,
                  "q8_epe_delta": 0.0,
                  "q8_fallbacks": 0}
    try:
        q8_metrics = _q8_serve_lane()
    except Exception as e:  # the q8 lane must never sink the scoreboard
        print(f"q8 serve bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)

    # Fleet lane (raft_tpu/serve/fleet.py): N=3 local replicas behind
    # the stream-affinity front door under a POISSON arrival process —
    # aggregate requests/s and the fleet-wide p95 join the scoreboard
    # next to the single-server serving lane.  The replicas share one
    # AOT cache (replica 0 compiles, the rest verify-and-load), and the
    # load runs video streams so the routing/spill path is exercised,
    # not just the dispatch path.
    def _fleet_lane(n_replicas=3):
        import tempfile

        from raft_tpu.serve.aot import AOTCache
        from raft_tpu.serve.engine import ServeEngine
        from raft_tpu.serve.fleet import FleetServer
        from raft_tpu.serve.server import FlowServer

        serve_vars = {"params": state.params}
        bs = getattr(state, "batch_stats", None)
        if bs:
            serve_vars["batch_stats"] = bs
        serve_b = min(2, B)
        td = tempfile.mkdtemp(prefix="bench_fleet_")
        aot = AOTCache(os.path.join(td, "aot"))

        def factory(rid, spill):
            eng = ServeEngine(RAFT(cfg), serve_vars, batch_size=serve_b,
                              aot_cache=aot)
            return FlowServer(eng, buckets={"bench": (H, W)},
                              queue_capacity=max(8, 4 * serve_b),
                              iter_levels=(iters,), degrade=False,
                              spill_store=spill)

        fleet = FleetServer(factory, n_replicas=n_replicas,
                            spill_dir=os.path.join(td, "spill"))
        try:
            fleet.warmup()
            rng_f = np.random.default_rng(13)

            def frame():
                return rng_f.uniform(0, 255, (H, W, 3)).astype(np.float32)

            # poisson arrivals at ~1.5x the measured single-server
            # rate (or a nominal rate when that lane failed): the lane
            # measures the fleet absorbing MORE than one replica's
            # capacity, which is the point of having a fleet
            single = serve_metrics.get("requests_per_s_per_chip") or 0.0
            rate = 1.5 * single if single > 0 else 10.0
            n_req = 6 if tiny else 36
            futs = []
            t0 = time.perf_counter()
            for i in range(n_req):
                futs.append(fleet.submit(frame(), frame(),
                                         stream=f"b{i % 6}"))
                time.sleep(float(rng_f.exponential(1.0 / rate)))
            for f in futs:
                f.result(timeout=600)
            wall = time.perf_counter() - t0
            summary = fleet.close()
            fleet = None
            return {
                "fleet_requests_per_s": round(n_req / wall, 3),
                "fleet_latency_p95_ms":
                    summary.get("latency_p95_ms", 0.0),
                "fleet_replicas": n_replicas,
            }
        finally:
            if fleet is not None:
                fleet.close()

    fleet_metrics = {"fleet_requests_per_s": 0.0,
                     "fleet_latency_p95_ms": 0.0,
                     "fleet_replicas": 0}
    try:
        fleet_metrics = _fleet_lane()
    except Exception as e:  # the fleet lane must never sink the scoreboard
        print(f"fleet bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)

    # Stereo workload lanes (raft_tpu/workloads/stereo): the SAME
    # architecture at 1D correlation, measured both ways the flow graph
    # is — a train-step lane at the bench config and a serving lane
    # through the real FlowServer with a stereo engine.  Random-init
    # weights (the lanes measure machinery rate, not accuracy).
    def _stereo_train_lane():
        from raft_tpu.training import create_train_state as _cts
        from raft_tpu.workloads.stereo import (StereoRAFT,
                                               make_stereo_train_step,
                                               stereo_config)

        s_cfg = stereo_config(overrides={
            "compute_dtype": cfg.compute_dtype,
            "corr_dtype": cfg.corr_dtype,
            "remat": cfg.remat, "remat_policy": cfg.remat_policy})
        s_model = StereoRAFT(s_cfg)
        s_batch = {
            "image1": batch["image1"], "image2": batch["image2"],
            "disp": jnp.asarray(
                rng.uniform(0, 32, (B, H, W)).astype(np.float32)),
            "valid": jnp.ones((B, H, W), np.float32),
        }
        tx2, _ = make_optimizer(lr=4e-4, num_steps=1000, wdecay=1e-4)
        s_state = _cts(s_model, tx2, jax.random.PRNGKey(1), s_batch,
                       iters=iters)
        s_step = make_stereo_train_step(s_model, iters=iters, donate=True)
        s_state, m = s_step(s_state, s_batch)
        float(m["loss"])                      # warmup + compile
        n = 2 if tiny else 10
        t0 = time.perf_counter()
        for _ in range(n):
            s_state, m = s_step(s_state, s_batch)
        float(m["loss"])
        return round(B * n / (time.perf_counter() - t0), 3)

    def _stereo_serve_lane():
        from raft_tpu.serve.engine import ServeEngine
        from raft_tpu.serve.server import FlowServer
        from raft_tpu.workloads.stereo import (STEREO_SERVE_OVERRIDES,
                                               StereoRAFT,
                                               compile_stereo_forward,
                                               stereo_config)

        s_model = StereoRAFT(stereo_config(
            overrides=STEREO_SERVE_OVERRIDES))
        init_img = np.zeros((1, H, W, 3), np.float32)
        s_vars = s_model.init(jax.random.PRNGKey(2), init_img, init_img,
                              iters=2, train=True)
        serve_b = min(2, B)
        engine = ServeEngine(s_model, s_vars, batch_size=serve_b,
                             compile_fn=compile_stereo_forward,
                             cache_tag="stereo_serve", warm_channels=1)
        server = FlowServer({"stereo": engine}, buckets={"bench": (H, W)},
                            queue_capacity=max(8, 4 * serve_b),
                            iter_levels=(iters,), degrade=False)
        try:
            server.warmup(warm_too=False)
            rng_s = np.random.default_rng(11)

            def frame():
                return rng_s.uniform(0, 255, (H, W, 3)).astype(np.float32)

            n_req = 4 if tiny else 24
            t0 = time.perf_counter()
            done = []
            for i in range(n_req):
                done.append(server.submit(frame(), frame(),
                                          workload="stereo"))
                if (i + 1) % serve_b == 0:
                    for f in done[-serve_b:]:
                        f.result(timeout=600)
            for f in done:
                f.result(timeout=600)
            wall = time.perf_counter() - t0
            summary = server.close()
            server = None
            return {
                "stereo_pairs_per_s_per_chip": round(n_req / wall, 3),
                "stereo_latency_p95_ms":
                    summary.get("latency_p95_ms", 0.0),
            }
        finally:
            if server is not None:
                server.close()

    def _confidence_overhead():
        """Percent step-time delta of the uncertainty head on the eval
        forward — the price of shipping confidence with every flow."""
        from raft_tpu.models import RAFT as _RAFT

        img = jnp.asarray(
            rng.uniform(0, 255, (1, H, W, 3)).astype(np.float32))
        # identical configs except the head flag: the delta measures
        # the head, not a config difference
        base = dataclasses.replace(cfg, remat=False, remat_policy="")
        times = {}
        for label, head in (("off", False), ("on", True)):
            m = _RAFT(dataclasses.replace(base, uncertainty_head=head))
            v = m.init(jax.random.PRNGKey(3), img, img, iters=2,
                       train=True)
            fwd = jax.jit(lambda variables, a, b, mm=m: mm.apply(
                variables, a, b, iters=iters, test_mode=True))
            out = fwd(v, img, img)
            np.asarray(out[0])                # warmup + compile
            n = 2 if tiny else 8
            t0 = time.perf_counter()
            for _ in range(n):
                out = fwd(v, img, img)
            np.asarray(out[0])
            times[label] = (time.perf_counter() - t0) / n
        return round(100.0 * (times["on"] - times["off"]) / times["off"],
                     2)

    def _fused_ab_lane():
        """Fused-vs-reference A/B on the train step: the headline
        already measures one side of RAFTConfig.fused_update_block, so
        this lane builds the OTHER side's executable and times it —
        the scoreboard carries both numbers every round (the
        deferred_corr_grad precedent: knobs stay measured, not
        asserted).  Never sinks the scoreboard."""
        other_cfg = dataclasses.replace(cfg,
                                        fused_update_block=not fused)
        o_step, o_state, _ = build(other_cfg)
        n = 2 if tiny else 10
        t0 = time.perf_counter()
        for _ in range(n):
            o_state, o_m = o_step(o_state, batch)
        float(o_m["loss"])
        other_rate = round(B * n / (time.perf_counter() - t0), 3)
        this_rate = round(pairs_per_s, 3)
        return {
            "fused_pairs_per_s": (this_rate if fused else other_rate),
            "reference_pairs_per_s": (other_rate if fused
                                      else this_rate),
            "benched": "fused" if fused else "reference",
        }

    fused_ab = {}
    try:
        fused_ab = _fused_ab_lane()
    except Exception as e:  # the A/B lane must never sink the scoreboard
        print(f"fused A/B bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)

    stereo_metrics = {"stereo_pairs_per_s": 0.0,
                      "stereo_pairs_per_s_per_chip": 0.0,
                      "stereo_latency_p95_ms": 0.0}
    try:
        stereo_metrics["stereo_pairs_per_s"] = _stereo_train_lane()
    except Exception as e:  # workload lanes never sink the scoreboard
        print(f"stereo train bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        stereo_metrics.update(_stereo_serve_lane())
    except Exception as e:
        print(f"stereo serve bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    confidence_overhead_pct = 0.0
    try:
        confidence_overhead_pct = _confidence_overhead()
    except Exception as e:
        print(f"confidence overhead bench failed: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
    # The headline fed lane mirrors the train CLI's auto policy: device
    # aug on an accelerator, host aug on a CPU backend (where the
    # matmul resample loses — an RAFT_BENCH_ALLOW_CPU smoke must not
    # report the lane production would never run).  Both lanes stay in
    # the output, so the comparison is always visible.
    fed_pairs_per_s = fed_dev if platform != "cpu" else fed_pairs_per_s_host
    fed_lane = "device" if platform != "cpu" else "host"

    # lane -> registered entry point whose graph the lane measures
    # (raft_tpu/entrypoints.py): the scoreboard and the graftlint
    # budget/audit ledgers talk about the same graphs by construction
    from raft_tpu.entrypoints import bench_lanes
    lane_entries = bench_lanes()
    # the fleet lane dispatches the same registered serve_forward
    # graphs as the single-server serving lane (the fleet is a routing
    # layer, not a new lowerable graph)
    lane_entries["fleet"] = "serve_forward"
    # per-lane predicted peak HBM from graftlint engine 8's committed
    # memory model (budgets.json "memory" section, keyed through the
    # same lane -> entry map) — lands next to the measured watermark so
    # the obs report can print predicted-vs-measured side by side;
    # lanes whose entry carries no memory row are omitted
    from raft_tpu.analysis.shard_audit import predicted_peak_map
    predicted_peak = {lane: peak for lane, peak
                      in predicted_peak_map(lane_entries).items()
                      if peak is not None}
    # the pod half of the perf story: the dryrun's 1->n device curve
    # for the ZeRO-sharded step, cited from its artifact
    pod_scaling = pod_scaling_stamp()

    if ledger is not None:
        ledger.close(summary=health.summary()
                     | {"pairs_per_s": round(pairs_per_s, 3),
                        "fed_pairs_per_s": round(fed_pairs_per_s, 3),
                        "fed_pairs_per_s_device": round(fed_dev, 3),
                        "fed_pairs_per_s_host":
                            round(fed_pairs_per_s_host, 3),
                        "fed_lane": fed_lane,
                        "predicted_peak_hbm_bytes": predicted_peak}
                     | ({"pod_scaling": pod_scaling} if pod_scaling
                        else {})
                     | serve_metrics | q8_metrics
                     | fleet_metrics | stereo_metrics
                     | sdc_metrics
                     | {"confidence_overhead_pct":
                            confidence_overhead_pct,
                        "fused_update_block": fused}
                     | ({"fused_ab": fused_ab} if fused_ab else {}))

    print(json.dumps({
        "metric": "image-pairs/sec/chip",
        "value": round(pairs_per_s, 3),
        "unit": "pairs/s",
        "vs_baseline": round(pairs_per_s / A100_BASELINE_PAIRS_PER_S, 3),
        "mfu": round(mfu, 4),
        # per-step-synced step-time tail (ms): the percentile lane above,
        # NOT the pipelined loop `value` is computed from
        "step_ms": {k: round(1000 * step_pct[k], 2)
                    for k in ("p50", "p95", "max")},
        "fed_pairs_per_s": round(fed_pairs_per_s, 3),
        "fed_lane": fed_lane,
        "fed_pairs_per_s_device": round(fed_dev, 3),
        "fed_pairs_per_s_host": round(fed_pairs_per_s_host, 3),
        # serving lane: synthetic requests through the real FlowServer
        # (queue -> batcher -> AOT executor) at this resolution
        **serve_metrics,
        # int8 serving lane (serve/quant.py, certified by graftlint
        # engine 7): same load through the QuantServeEngine, plus the
        # q8-vs-bf16 EPE delta and the in-range fallback count
        **q8_metrics,
        # fleet lane: N=3 local replicas behind the stream-affinity
        # front door under poisson arrivals (serve/fleet.py)
        **fleet_metrics,
        # stereo workload lanes: the same architecture at 1D corr —
        # train-step rate and serve rate through a stereo-engine server
        **stereo_metrics,
        # the uncertainty head's eval-forward cost (percent step delta)
        "confidence_overhead_pct": confidence_overhead_pct,
        # the silent-corruption defense's per-cadence cost at
        # --sdc_vote_every 100, as a percent of 100 steps' p50 wall
        **sdc_metrics,
        # which registered entry point each lane exercises
        "lane_entrypoints": lane_entries,
        # engine 8's predicted peak bytes per lane (committed memory
        # model; advisory next to the measured watermark — CPU hosts
        # measure host RSS, not HBM)
        "predicted_peak_hbm_bytes": predicted_peak,
        # per-device-count throughput + scaling efficiency of the
        # ZeRO-sharded step, from the newest dryrun_multichip artifact
        **({"pod_scaling": pod_scaling} if pod_scaling else {}),
        "host_cores": os.cpu_count(),
        "deferred_corr_grad": deferred,
        # which update-block implementation the headline (and the serve
        # lane, which shares cfg) actually ran, plus the fused-vs-
        # reference A/B sub-lane measuring the other side
        "fused_update_block": fused,
        **({"fused_ab": fused_ab} if fused_ab else {}),
        # degradations that fired while building the headline step —
        # empty means the configured default compiled as-is
        "fallbacks": fallbacks,
        **({"tiny": True} if tiny else {}),
    }))


if __name__ == "__main__":
    main()
