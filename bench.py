"""Benchmark runner: FlyingChairs-config training throughput on one chip.

Prints ONE JSON line:
  {"metric": "image-pairs/sec/chip", "value": N, "unit": "pairs/s",
   "vs_baseline": N}

Measured config mirrors the reference's mixed-precision chairs recipe
(train_mixed.sh:3: batch 8, crop 368x496, 12 refinement iterations,
bf16 compute) — the primary metric named in BASELINE.json.

Baseline: the reference repo publishes no numbers (BASELINE.md).  The
denominator used here is 7.0 pairs/s — an A100 estimate derived from the
RAFT paper's training-time claim (chairs 100k steps, batch 10, ~10 h on
two 2080 Ti => ~2.8 pairs/s/GPU, scaled by the ~2.5x A100/2080Ti training
speedup).  vs_baseline = measured / 7.0, so 2.0 meets the north-star
"2x A100 pairs/sec/chip" target.
"""

import json
import time

import numpy as np

A100_BASELINE_PAIRS_PER_S = 7.0


def main():
    import jax
    import jax.numpy as jnp

    from raft_tpu.config import RAFTConfig
    from raft_tpu.models import RAFT
    from raft_tpu.training import create_train_state, make_optimizer
    from raft_tpu.training.step import make_train_step

    import dataclasses

    from raft_tpu.config import STAGE_PRESETS

    # The measured config IS the chairs_mixed stage preset (reference's
    # train_mixed.sh recipe), so bench and training can't drift apart;
    # scripts/perf_probe.py derives its variants from the same source.
    preset = STAGE_PRESETS["chairs_mixed"]
    B = preset.data.batch_size
    H, W = preset.data.image_size
    iters = preset.train.iters

    rng = np.random.default_rng(0)
    batch = {
        "image1": jnp.asarray(rng.uniform(0, 255, (B, H, W, 3)).astype(np.float32)),
        "image2": jnp.asarray(rng.uniform(0, 255, (B, H, W, 3)).astype(np.float32)),
        "flow": jnp.asarray((rng.standard_normal((B, H, W, 2)) * 5).astype(np.float32)),
        "valid": jnp.ones((B, H, W), np.float32),
    }

    # remat=True (from the preset): without it the unrolled 12-iteration
    # scan needs ~21 GB of HBM at this resolution (v5e has 15.75 GB).
    # dots_saveable keeps matmul outputs and recomputes only elementwise
    # work: 16.0 pairs/s vs 14.2 for full recompute on v5e.
    # corr_dtype=bfloat16 halves the volume traffic and runs the lookup
    # matmuls at full MXU rate (f32 accumulation; ~0.5% relative error).
    cfg = dataclasses.replace(preset.model, corr_dtype="bfloat16")
    model = RAFT(cfg)
    tx, _ = make_optimizer(lr=4e-4, num_steps=1000, wdecay=1e-4)
    state = create_train_state(model, tx, jax.random.PRNGKey(0), batch,
                               iters=iters)
    step = make_train_step(model, iters=iters, gamma=0.8, max_flow=400.0,
                           donate=True)

    # Warmup / compile.  Synchronization must be a host copy: over the
    # axon tunnel, block_until_ready returns before execution finishes,
    # which silently times dispatch instead of compute.
    state, metrics = step(state, batch)
    float(metrics["loss"])

    n_steps = 10
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step(state, batch)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    pairs_per_s = B * n_steps / dt
    print(json.dumps({
        "metric": "image-pairs/sec/chip",
        "value": round(pairs_per_s, 3),
        "unit": "pairs/s",
        "vs_baseline": round(pairs_per_s / A100_BASELINE_PAIRS_PER_S, 3),
    }))


if __name__ == "__main__":
    main()
